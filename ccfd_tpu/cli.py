"""Command-line entry points: the reference run-book as one binary.

The reference's "entry point" is a 600-line oc-apply run-book whose step
order is a dependency sort (SURVEY.md §3 D). Here the same topology boots
in-process:

  python -m ccfd_tpu demo    # full pipeline: produce -> route -> score ->
                             # process -> notify -> retrain, prints metrics
  python -m ccfd_tpu serve   # REST scorer (Seldon contract) on a port
  python -m ccfd_tpu train   # offline-train the flagship MLP + checkpoint
  python -m ccfd_tpu bench   # the benchmark JSON line (same as bench.py)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Any

from ccfd_tpu.config import Config


def cmd_demo(args: argparse.Namespace) -> int:
    import jax

    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import load_dataset
    from ccfd_tpu.metrics.prom import Registry
    from ccfd_tpu.notify.service import NotificationService
    from ccfd_tpu.parallel.online import OnlineTrainer
    from ccfd_tpu.parallel.train import TrainConfig, fit_mlp
    from ccfd_tpu.process.fraud import build_engine
    from ccfd_tpu.process.prediction import ScorerPredictionService
    from ccfd_tpu.producer.producer import Producer
    from ccfd_tpu.router.router import Router
    from ccfd_tpu.serving.scorer import Scorer

    import dataclasses

    cfg = dataclasses.replace(
        Config.from_env(), customer_reply_timeout_s=args.reply_timeout
    )
    ds = load_dataset(n_synthetic=max(args.transactions, 4000))
    print(f"[demo] dataset: {ds.n} rows; training flagship MLP...", file=sys.stderr)
    params = fit_mlp(
        ds.X, ds.y, steps=args.train_steps, tc=TrainConfig(compute_dtype="float32")
    )

    broker = Broker(log_dir=cfg.bus_log_dir or None, fsync=cfg.bus_fsync,
                    retention_records=cfg.bus_retention_records or None,
                    retention_overrides=cfg.parsed_retention_overrides())
    reg_router, reg_kie, reg_notify, reg_retrain = (
        Registry(), Registry(), Registry(), Registry(),
    )
    scorer = Scorer(model_name="mlp", params=params, compute_dtype=cfg.compute_dtype,
                    dispatch_deadline_ms=cfg.scorer_dispatch_deadline_ms())
    scorer.warmup()
    engine = build_engine(
        cfg, broker, reg_kie,
        prediction_service=ScorerPredictionService(scorer.score),
    )
    router = Router(cfg, broker, scorer.score, engine, reg_router)
    notify = NotificationService(cfg, broker, reg_notify, seed=args.seed)
    trainer = OnlineTrainer(cfg, broker, scorer, params, registry=reg_retrain)

    _tune_gc()  # before the hot loops start: freeze races live churn
    router.start(poll_timeout_s=0.02)
    notify.start(poll_timeout_s=0.02)
    trainer.start(interval_s=0.5)

    t0 = time.perf_counter()
    Producer(cfg, broker, ds).run(
        limit=args.transactions,
        rate_per_s=args.rate,
        wire_format=args.wire_format,
    )
    # drain: wait until the router consumed everything + timers fired
    deadline = time.monotonic() + args.drain_s
    while time.monotonic() < deadline:
        if reg_router.counter("transaction_incoming_total").value() >= args.transactions:
            break
        time.sleep(0.1)
    time.sleep(args.reply_timeout + 1.0)
    elapsed = time.perf_counter() - t0
    router.stop(); notify.stop(); trainer.stop()

    out = reg_router.counter("transaction_outgoing_total")
    summary = {
        "transactions": int(reg_router.counter("transaction_incoming_total").value()),
        "fraud_routed": int(out.value({"type": "fraud"})),
        "standard_routed": int(out.value({"type": "standard"})),
        "notifications": int(reg_router.counter("notifications_outgoing_total").value()),
        "approved_amount_n": reg_kie.histogram("fraud_approved_amount").count(),
        "rejected_amount_n": reg_kie.histogram("fraud_rejected_amount").count(),
        "low_amount_auto_n": reg_kie.histogram("fraud_approved_low_amount").count(),
        "investigations_n": reg_kie.histogram("fraud_investigation_amount").count(),
        "open_tasks": len(engine.tasks()),
        "retrain_swaps": int(reg_retrain.counter("retrain_param_swaps_total").value()),
        "wall_s": round(elapsed, 2),
        "backend": jax.default_backend(),
    }
    print(json.dumps(summary))
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import load_dataset
    from ccfd_tpu.parallel.train import TrainConfig, fit_mlp
    from ccfd_tpu.serving.scorer import Scorer
    from ccfd_tpu.serving.server import PredictionServer

    cfg = Config.from_env()
    if cfg.graph_cr:
        # Serve a whole SeldonDeployment-shaped inference graph (ensemble /
        # router / transformer tree) compiled to one jitted callable.
        from ccfd_tpu.serving.graph import load_graph_cr

        if args.train:
            print(
                "[serve] --train trains the MLP; a CCFD_GRAPH_CR graph has "
                "graph-shaped params — unset --train or unset CCFD_GRAPH_CR",
                file=sys.stderr,
            )
            return 2
        spec = load_graph_cr(cfg.graph_cr)
        cfg = dataclasses.replace(cfg, model_name=spec.name)
    params = None
    if args.train:
        if cfg.model_name != "mlp":
            print(
                f"[serve] --train trains the MLP; CCFD_MODEL={cfg.model_name!r} "
                "params would not match — unset --train or set CCFD_MODEL=mlp",
                file=sys.stderr,
            )
            return 2
        ds = load_dataset()
        params = fit_mlp(ds.X, ds.y, steps=args.train_steps,
                         tc=TrainConfig(compute_dtype="float32"))
    elif cfg.model_name == "mlp":
        # serve the newest `train` checkpoint when one exists: training and
        # serving compose through the checkpoint dir, so `ccfd_tpu train`
        # followed by `ccfd_tpu serve` serves the trained (AUC-recorded)
        # params instead of random init
        params = _restore_mlp_checkpoint(getattr(args, "checkpoint_dir", ""))
    elif cfg.model_name == "mlp_q8":
        # int8 lifecycle: `train` -> `quantize` -> CCFD_MODEL=mlp_q8 serve
        params = _restore_q8_checkpoint(getattr(args, "quantized_dir", ""))
    elif cfg.model_name == "gbt":
        # tree lifecycle: `train --family hgb` -> CCFD_MODEL=gbt serve
        params = _restore_gbt_params(getattr(args, "gbt_dir", ""))
    scorer = Scorer(
        model_name=cfg.model_name, params=params, compute_dtype=cfg.compute_dtype,
        batch_sizes=cfg.batch_sizes,
        host_tier_rows=None if cfg.host_tier_rows < 0 else cfg.host_tier_rows,
        dispatch_deadline_ms=cfg.scorer_dispatch_deadline_ms(),
    )
    scorer.warmup()
    _tune_gc()
    srv = PredictionServer(scorer, cfg)
    port = srv.start(args.host, args.port)
    print(f"[serve] model={cfg.model_name} listening on {args.host}:{port}",
          file=sys.stderr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def _training_dataset():
    """The dataset model-lifecycle commands (train/quantize) run on: the
    real Kaggle table when CCFD_CSV points at it, else the committed
    deterministic Kaggle-shaped surrogate (data/surrogate.py) — never the
    small test synthetic, so shipped checkpoints always carry full-scale
    quality evidence."""
    from ccfd_tpu.data.ccfd import load_dataset

    if os.environ.get("CCFD_CSV"):
        return load_dataset(), os.environ["CCFD_CSV"]
    from ccfd_tpu.data.surrogate import SURROGATE_VERSION, kaggle_surrogate

    # CCFD_SURROGATE_ROWS shrinks the dataset for fast CI/unit runs; the
    # default (full 284,807 rows) is what shipped artifacts train on
    rows = int(os.environ.get("CCFD_SURROGATE_ROWS", "0") or 0)
    if rows > 0:
        return kaggle_surrogate(n=rows), f"surrogate:{SURROGATE_VERSION}:n={rows}"
    return kaggle_surrogate(), f"surrogate:{SURROGATE_VERSION}"


def cmd_train(args: argparse.Namespace) -> int:
    """Offline training with the reference's data path: the CSV comes from
    the object store (reference README.md:303-343 uploads creditcard.csv to
    S3 and every consumer reads it from there) via ``--from-store``, from a
    local file via CCFD_CSV, else the synthetic surrogate. Records held-out
    AUC for the trained MLP AND the sklearn LogReg baseline (the reference's
    modelfull is a sklearn classifier) so every checkpoint ships with its
    quality evidence; the checkpoint it writes is what ``serve`` loads by
    default."""
    import numpy as np

    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import load_csv_bytes
    from ccfd_tpu.models import mlp as mlp_mod
    from ccfd_tpu.parallel.checkpoint import CheckpointManager
    from ccfd_tpu.parallel.train import TrainConfig, fit_mlp
    from ccfd_tpu.utils.metrics_math import roc_auc

    cfg = Config.from_env()
    source = "synthetic"
    if args.from_store:
        from ccfd_tpu.store.client import S3Client
        from ccfd_tpu.store.objectstore import Credentials

        client = S3Client(
            args.store_url or cfg.s3_endpoint or "http://127.0.0.1:9000",
            Credentials(cfg.access_key_id or "ccfd-access",
                        cfg.secret_access_key or "ccfd-secret"),
        )
        ds = load_csv_bytes(client.get(cfg.s3_bucket, cfg.filename))
        source = f"store:{cfg.s3_bucket}/{cfg.filename}"
    else:
        ds, source = _training_dataset()

    # held-out split for honest AUC (stratification unnecessary at 284k rows;
    # the tail is sorted by Time in the real CSV, so shuffle first)
    rng = np.random.default_rng(0)
    order = rng.permutation(ds.n)
    n_test = max(1, int(ds.n * args.test_frac))
    test, train = order[:n_test], order[n_test:]
    Xtr, ytr, Xte, yte = ds.X[train], ds.y[train], ds.X[test], ds.y[test]

    if getattr(args, "family", "mlp") == "hgb":
        # the strongest reference-family model, made servable: sklearn
        # HistGradientBoosting (bounded depth) -> the served dense-tree
        # params (models/trees.py from_sklearn_hgb; HGB_SERVABLE_r04.json
        # has the depth sweep). CCFD_MODEL=gbt serve restores the result.
        import jax.numpy as jnp

        from ccfd_tpu.models import trees as trees_mod

        try:
            from sklearn.ensemble import HistGradientBoostingClassifier
        except ImportError:
            print("[train] --family hgb needs scikit-learn", file=sys.stderr)
            return 2
        if args.hgb_depth > 10:
            # fail BEFORE the minutes-long fit: the dense embedding is
            # 2^depth nodes/tree and the converter refuses deeper trees
            print(f"[train] --hgb-depth {args.hgb_depth} > 10: the dense "
                  "embedding is 2^depth nodes/tree (see "
                  "trees.from_sklearn_hgb)", file=sys.stderr)
            return 2
        clf = HistGradientBoostingClassifier(
            max_depth=args.hgb_depth, class_weight="balanced",
            random_state=0,
        ).fit(Xtr, ytr)
        gbt_params = trees_mod.from_sklearn_hgb(clf)
        served = np.asarray(trees_mod.apply(gbt_params, jnp.asarray(Xte)))
        conv_delta = float(
            np.abs(served - clf.predict_proba(Xte)[:, 1]).max()
        )
        path = _save_gbt_params(args.gbt_dir, gbt_params)
        print(json.dumps({
            "checkpoint": path, "rows": int(ds.n), "family": "hgb",
            "max_depth": args.hgb_depth, "source": source,
            "test_rows": int(n_test),
            "auc_hgb_served": round(roc_auc(yte, served), 5),
            "conversion_max_prob_delta": conv_delta,
        }))
        return 0

    params = fit_mlp(Xtr, ytr, steps=args.steps,
                     tc=TrainConfig(compute_dtype="float32"))
    proba = np.asarray(mlp_mod.apply(params, Xte))
    auc_mlp = roc_auc(yte, proba)

    auc_ref = None
    try:
        from sklearn.linear_model import LogisticRegression
        from sklearn.preprocessing import StandardScaler

        sc = StandardScaler().fit(Xtr)
        clf = LogisticRegression(max_iter=1000).fit(sc.transform(Xtr), ytr)
        auc_ref = roc_auc(yte, clf.predict_proba(sc.transform(Xte))[:, 1])
    except ImportError:
        pass  # baseline AUC simply absent without sklearn

    path = CheckpointManager(args.checkpoint_dir).save(args.steps, params)
    print(json.dumps({
        "checkpoint": path, "rows": int(ds.n), "steps": args.steps,
        "source": source, "test_rows": int(n_test),
        "auc_mlp": round(auc_mlp, 5),
        "auc_sklearn_logreg": round(auc_ref, 5) if auc_ref is not None else None,
    }))
    return 0


def _restore_checkpoint(checkpoint_dir: str, like):
    """Latest checkpoint structured like ``like``, or None."""
    if not checkpoint_dir:
        return None
    from ccfd_tpu.parallel.checkpoint import CheckpointManager

    mgr = CheckpointManager(checkpoint_dir)
    if mgr.latest_step() is None:
        return None
    restored = mgr.restore(like)
    if restored is None:
        return None
    params, step = restored
    print(f"[checkpoint] restored step={step} from {checkpoint_dir}",
          file=sys.stderr)
    return params


_Q8_DIR = "./checkpoints_q8"  # quantize writes here; serve/score read it
_GBT_DIR = "./checkpoints_gbt"  # train --family hgb writes here


def _save_gbt_params(gbt_dir: str, params) -> str:
    """Dense-tree params (models/trees.py layout) -> one npz. The tree
    family's artifact is four arrays, not an optimizer-bearing pytree, so
    a plain npz beats an orbax checkpoint here (humanly inspectable,
    loadable without the model's init shapes)."""
    import io

    import numpy as np

    from ccfd_tpu.runtime.durability import write_artifact

    d = gbt_dir or _GBT_DIR
    path = os.path.join(d, "params.npz")
    # checksummed atomic swap (runtime/durability.py — the hand-rolled
    # tmp+rename here skipped the fsync, so a power loss could lose BOTH
    # copies): a crash mid-save or a reader racing a refresh never sees a
    # half-written artifact, and a corrupt file falls back to the
    # retained last-good generation on read
    buf = io.BytesIO()
    np.savez(
        buf,
        feature=np.asarray(params["feature"]),
        threshold=np.asarray(params["threshold"]),
        leaf=np.asarray(params["leaf"]),
        base=np.asarray(params["base"]),
    )
    write_artifact(path, buf.getvalue(), artifact="gbt_params",
                   best_effort=False)
    return path


def _restore_gbt_params(gbt_dir: str):
    """The `train --family hgb` artifact as served gbt params, or None."""
    import io
    import zipfile

    import jax.numpy as jnp
    import numpy as np

    from ccfd_tpu.runtime.durability import (
        CorruptArtifactError,
        read_artifact,
    )

    path = os.path.join(gbt_dir or _GBT_DIR, "params.npz")
    try:
        # verified read: a corrupt file quarantines and the last-good
        # retained generation serves; legacy unframed files still load
        raw = read_artifact(path, artifact="gbt_params")
        with np.load(io.BytesIO(raw)) as z:
            params = {k: jnp.asarray(z[k])
                      for k in ("feature", "threshold", "leaf", "base")}
    except FileNotFoundError:
        return None
    # BadZipFile subclasses Exception directly — a truncated npz raises it
    except (OSError, ValueError, KeyError, zipfile.BadZipFile,
            CorruptArtifactError) as e:
        print(f"[checkpoint] unreadable gbt params at {path} ({e!r}); "
              "serving fresh init", file=sys.stderr)
        return None
    print(f"[checkpoint] restored gbt params from {path}", file=sys.stderr)
    return params


def _restore_mlp_checkpoint(checkpoint_dir: str):
    """Latest `train` checkpoint as MLP params, or None. The checkpoint
    format is the MLP's pytree, so callers must only apply this when the
    configured model is the MLP (serve and score share this guard)."""
    import jax

    from ccfd_tpu.models import mlp as mlp_mod

    return _restore_checkpoint(
        checkpoint_dir, mlp_mod.init(jax.random.PRNGKey(0))
    )


def _restore_q8_checkpoint(quantized_dir: str):
    """Latest `quantize` checkpoint as mlp_q8 params, or None."""
    from ccfd_tpu.models.registry import get_model

    return _restore_checkpoint(quantized_dir or _Q8_DIR,
                               get_model("mlp_q8").init())


def cmd_quantize(args: argparse.Namespace) -> int:
    """Model-lifecycle step between `train` and `serve`: load the newest
    f32 MLP checkpoint, emit int8 params (ops/quant.py) plus evidence
    that quantization kept the model's quality. The evidence is the
    f32-to-int8 DELTA (AUC and probability) on a sampled evaluation set —
    both models score identical rows, so the delta is valid even if this
    run's dataset/sample differs from the train run's held-out split;
    absolute held-out AUC is `train`'s claim, recorded at training time."""
    import jax
    import numpy as np

    from ccfd_tpu.models import mlp as mlp_mod
    from ccfd_tpu.ops import quant
    from ccfd_tpu.parallel.checkpoint import CheckpointManager
    from ccfd_tpu.utils.metrics_math import roc_auc

    mgr = CheckpointManager(args.checkpoint_dir)
    step = mgr.latest_step()
    if step is None:
        print(
            f"[quantize] no checkpoint in {args.checkpoint_dir!r}; "
            "run `ccfd_tpu train` first",
            file=sys.stderr,
        )
        return 2
    params, step = mgr.restore(mlp_mod.init(jax.random.PRNGKey(0)))
    qp = quant.quantize_mlp(params)

    ds, _source = _training_dataset()
    rng = np.random.default_rng(0)
    te = rng.permutation(ds.n)[: max(1, int(ds.n * args.test_frac))]
    p32 = np.asarray(mlp_mod.apply(params, ds.X[te]))
    p8 = quant.apply_numpy(jax.tree.map(np.asarray, qp), ds.X[te])
    path = CheckpointManager(args.out_dir).save(step, qp)
    print(json.dumps({
        "source_step": step,
        "eval_rows": int(len(te)),
        "auc_f32": round(roc_auc(ds.y[te], p32), 6),
        "auc_int8": round(roc_auc(ds.y[te], p8), 6),
        "max_prob_delta": round(float(np.abs(p8 - p32).max()), 6),
        # the claim: f32 vs int8 on IDENTICAL rows (quantization delta);
        # absolute held-out AUC lives in the train command's record
        "evidence": "f32-to-int8 delta on a sampled evaluation set",
        "checkpoint": path,
        "serve_with": "CCFD_MODEL=mlp_q8 ccfd_tpu serve",
    }))
    return 0


def _audit_fetch_json(url: str):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError, ValueError):
        return None


def cmd_audit_reconstruct(args: argparse.Namespace, cfg) -> int:
    """``ccfd_tpu audit <tx_id>``: the regulator question, answered from
    one command — the DecisionRecord stamped at the route seam, joined
    to the lifecycle lineage (version + checkpoint hash, with a parity
    verdict), the incident bundle open when the decision was made, and
    the kept trace when the tail sampler sampled it. Reads the live
    exporter with ``--url``; otherwise reconstructs OFFLINE from the
    on-disk artifacts — which is exactly what a crash-restore drill
    exercises (tools/audit_smoke.py)."""
    doc: dict = {"tx_id": args.tx_id}
    record = None
    base = args.url.rstrip("/") if args.url else ""
    if base:
        record = _audit_fetch_json(f"{base}/decisions/{args.tx_id}")
    if record is None:
        audit_dir = args.dir or cfg.audit_dir
        if audit_dir:
            from ccfd_tpu.observability.audit import AuditLog

            # readonly: an inspection command must never truncate the
            # live log out from under a running platform. The ring is
            # sized from config so recovery rebuilds as deep a view as
            # the configured platform would (CCFD_AUDIT_RING).
            log = AuditLog(dir=audit_dir, readonly=True,
                           max_records=cfg.audit_ring)
            record = log.get(args.tx_id)
    if record is None:
        print(f"[audit] no decision record for {args.tx_id!r} (checked "
              + (f"{base}/decisions and " if base else "")
              + f"dir={args.dir or cfg.audit_dir or '<unset>'})",
              file=sys.stderr)
        return 2
    doc["record"] = record

    # -- lineage join: the version that scored it, hash parity ------------
    lc_dir = args.lifecycle_dir or cfg.lifecycle_dir
    if lc_dir and record.get("version") is not None:
        from ccfd_tpu.lifecycle.versions import VersionStore

        path = os.path.join(lc_dir, "versions.json")
        try:
            store = VersionStore(path, recover=False)
            v = store.get(int(record["version"]))
            doc["lineage"] = {
                "version": v.to_dict(),
                "events": store.audit_trail(v.version),
                # the compliance check: the hash stamped on the decision
                # equals the hash the lineage records for that version
                "hash_parity": (record.get("hash") is not None
                                and v.checkpoint_hash == record.get("hash")),
            }
        except (OSError, ValueError, KeyError, TypeError) as e:
            doc["lineage"] = {"error": repr(e)}

    # -- incident join: what was burning while this decision was made -----
    inc_id = record.get("incident")
    if inc_id:
        bundle = None
        if base:
            bundle = _audit_fetch_json(f"{base}/incidents/{inc_id}")
        if bundle is None:
            inc_dir = args.incident_dir or cfg.incident_dir
            if inc_dir:
                try:
                    with open(os.path.join(inc_dir, f"{inc_id}.json")) as f:
                        bundle = json.load(f)
                except (OSError, ValueError):
                    bundle = None
        if bundle is not None:
            doc["incident"] = {
                "id": bundle.get("id"),
                "trigger": bundle.get("trigger"),
                "generated_unix": bundle.get("generated_unix"),
                "found": True,
            }
        else:
            doc["incident"] = {"id": inc_id, "found": False}

    # -- trace join: only the live sink holds kept traces -----------------
    trace_id = record.get("trace")
    if trace_id and base:
        tr = _audit_fetch_json(f"{base}/traces/{trace_id}")
        doc["trace"] = ({"trace_id": trace_id,
                         "spans": len(tr.get("spans", [])), "kept": True}
                        if tr is not None
                        else {"trace_id": trace_id, "kept": False})
    elif trace_id:
        doc["trace"] = {"trace_id": trace_id, "kept": None}

    if args.json:
        print(json.dumps(doc, indent=1, default=str))
        return 0
    r = record
    print(f"decision tx={r.get('tx')} uid={r.get('uid')} seq={r.get('seq')}")
    print(f"  score: proba={r.get('proba')} threshold={r.get('threshold')} "
          f"-> rule={r.get('rule')} branch={r.get('branch')} "
          f"pid={r.get('pid')}")
    tier = r.get("tier", "?")
    cause = f" ({r['cause']})" if r.get("cause") else ""
    print(f"  served by: {tier} tier{cause}  priority={r.get('priority')}"
          + (f"  events={r['events']}" if r.get("events") else ""))
    print(f"  model: version={r.get('version')} hash={r.get('hash')}")
    lin = doc.get("lineage")
    if lin and "version" in lin:
        parity = "OK" if lin["hash_parity"] else "MISMATCH"
        v = lin["version"]
        print(f"  lineage: v{v['version']} stage={v['stage']} "
              f"ckpt={v['checkpoint_step']} hash parity: {parity} "
              f"({len(lin['events'])} audit events)")
    inc = doc.get("incident")
    if inc:
        mark = "" if inc.get("found") else " (bundle not found)"
        print(f"  incident: {inc['id']}{mark}"
              + (f" trigger={inc['trigger']}" if inc.get("trigger") else ""))
    trc = doc.get("trace")
    if trc:
        kept = {True: "kept", False: "not retained",
                None: "offline (query --url for spans)"}[trc.get("kept")]
        print(f"  trace: {trc['trace_id']} [{kept}]")
    return 0


def cmd_audit(args: argparse.Namespace) -> int:
    """With a tx id: reconstruct that decision end-to-end (provenance
    plane, observability/audit.py). Without one: tail the engine's audit
    stream (CCFD_AUDIT_TOPIC) — one JSON event per line, the operator
    view of jBPM's process-instance history. ``--follow`` keeps
    consuming; otherwise drains what's there and exits."""
    from ccfd_tpu.config import Config

    cfg = Config.from_env()
    if args.tx_id:
        return cmd_audit_reconstruct(args, cfg)
    topic = args.topic or cfg.audit_topic
    if not topic:
        # surface the misconfiguration instead of an empty-but-successful
        # tail: without CCFD_AUDIT_TOPIC the engine emits nothing
        print(
            "[audit] CCFD_AUDIT_TOPIC is unset (the engine's audit stream "
            "is OFF); tailing the default topic 'ccd-audit'",
            file=sys.stderr,
        )
        topic = "ccd-audit"
    broker = _broker_for(cfg)
    consumer = broker.consumer(args.group, (topic,))
    printed = 0
    try:
        while True:
            # cap the fetch at the remaining limit: poll auto-commits what
            # it returns, and over-fetching would silently skip events the
            # group never printed
            want = min(1024, args.limit - printed) if args.limit else 1024
            recs = consumer.poll(want, 0.5 if args.follow else 0.0)
            for rec in recs:
                print(json.dumps(rec.value))
                printed += 1
                if args.limit and printed >= args.limit:
                    return 0
            if not recs and not args.follow:
                return 0
    except KeyboardInterrupt:
        return 0
    finally:
        consumer.close()


def cmd_replay(args: argparse.Namespace) -> int:
    """``ccfd_tpu replay``: the bulk replay & backtest console (replay/).

    Offline (default): scan the recorded window out of the audit
    segments read-only and summarize it; with ``--what-if-threshold``
    run the host-side backtest diff (which recorded decisions flip under
    the new threshold) — no platform, no bus. With ``--live``: bring the
    platform up, re-produce the window through the real
    producer→bus→router→scorer path under ``bulk`` admission, and print
    the verdict-parity report (divergences classified by cause)."""
    from ccfd_tpu.config import Config

    cfg = Config.from_env()
    audit_dir = args.dir or cfg.audit_dir
    if not audit_dir:
        print("[replay] no audit dir: pass --dir or set CCFD_AUDIT_DIR "
              "(windows are reconstructed from the audit segments)",
              file=sys.stderr)
        return 2
    since, until = args.since_seq, args.until_seq
    if args.from_incident:
        from ccfd_tpu.replay.service import bundle_window

        with open(args.from_incident) as f:
            rng = bundle_window(json.load(f))
        if rng is None:
            print(f"[replay] {args.from_incident} embeds no decision "
                  "summaries; nothing to re-drive", file=sys.stderr)
            return 2
        since, until = rng

    if args.live:
        _honor_platform_env()
        _probe_backend_or_fallback()
        from ccfd_tpu.platform.operator import Platform, PlatformSpec

        if args.cr:
            spec = PlatformSpec.from_yaml(args.cr, cfg=cfg)
        else:
            # minimal replay platform: bus + scorer + engine + router +
            # the audit/replay planes over the recorded segments
            spec = PlatformSpec.from_cr({"spec": {
                "audit": {"dir": audit_dir},
                "replay": {"enabled": True,
                           "dir": args.state_dir or cfg.replay_dir},
                "monitoring": {"enabled": False},
                "health": {"enabled": False},
                "analytics": {"enabled": False},
                "retrain": {"enabled": False},
                "notify": {"enabled": False},
            }}, cfg=cfg)
        p = Platform(spec).up()
        try:
            if p.replay is None:
                print("[replay] the platform came up without the replay "
                      "component (CR replay.enabled / audit plane off?)",
                      file=sys.stderr)
                return 2
            report = p.replay.run_window(
                since, until,
                window_id=(args.window_id or None),
                resume=not args.no_resume)
        finally:
            p.down()
        print(json.dumps(report if args.json else {
            k: report[k] for k in ("window_id", "total", "replayed",
                                   "match", "divergence", "drop", "ghost",
                                   "causes", "parity", "rows_per_s")}))
        return 0 if report.get("parity") else 1

    from ccfd_tpu.observability.audit import AuditLog
    from ccfd_tpu.replay.service import ReplayService

    audit = AuditLog(dir=audit_dir, readonly=True,
                     max_records=cfg.audit_ring)
    if args.what_if_threshold is not None:
        svc = ReplayService(cfg, None, audit,
                            state_dir=(args.state_dir or None))
        report = svc.run_window(since, until, mode="whatif",
                                threshold=args.what_if_threshold,
                                window_id=(args.window_id or None))
        print(json.dumps(report if args.json else {
            k: report[k] for k in ("window_id", "total", "threshold",
                                   "flips", "flip_rate",
                                   "mean_abs_delta")}))
        return 0
    recs = audit.scan_window(since, until)
    tiers: dict[str, int] = {}
    for r in recs:
        t = str(r.get("tier", "device"))
        tiers[t] = tiers.get(t, 0) + 1
    doc = {
        "records": len(recs),
        "rescorable": sum(1 for r in recs if r.get("row") is not None),
        "seq": ([int(recs[0].get("seq", -1)),
                 int(recs[-1].get("seq", -1))] if recs else None),
        "tiers": tiers,
    }
    print(json.dumps(doc))
    return 0


def cmd_lifecycle(args: argparse.Namespace) -> int:
    """Model-lifecycle console: the versioned lineage + transition audit
    trail the controller persists (lifecycle/versions.py). Reads the
    store the platform's ``lifecycle.state_dir`` (or CCFD_LIFECYCLE_DIR)
    points at — the compliance question "which model served when, trained
    on which labels, and why was it promoted/rolled back" answered from
    one JSON file, no running platform needed."""
    from ccfd_tpu.lifecycle.versions import VersionStore

    cfg = Config.from_env()
    state_dir = args.dir or cfg.lifecycle_dir
    if not state_dir:
        print("[lifecycle] no state dir: pass --dir or set "
              "CCFD_LIFECYCLE_DIR (the CR's lifecycle.state_dir)",
              file=sys.stderr)
        return 2
    path = os.path.join(state_dir, "versions.json")
    if not os.path.exists(path):
        print(f"[lifecycle] no lineage at {path}", file=sys.stderr)
        return 2
    try:
        # recover=False: an INSPECTION command must never quarantine the
        # live lineage file out from under a running platform — report
        # the corruption and let the controller's own recovery handle it
        store = VersionStore(path, recover=False)
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"[lifecycle] lineage at {path} is unreadable ({e!r}); the "
              "controller quarantines and re-bootstraps it at next "
              "bring-up", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({
            "versions": [v.to_dict() for v in store.versions()],
            "audit": store.audit_trail(args.version or None),
        }, indent=1))
        return 0
    champ = store.champion()
    print(f"champion: v{champ.version}" if champ else "champion: none")
    for v in store.versions():
        mark = "*" if champ and v.version == champ.version else " "
        print(f"{mark} v{v.version:<4} stage={v.stage:<12} "
              f"parent={v.parent if v.parent is not None else '-':<4} "
              f"labels@{v.label_watermark:<8} "
              f"ckpt={v.checkpoint_step if v.checkpoint_step is not None else '-'}")
    if args.audit:
        for e in store.audit_trail(args.version or None):
            detail = json.dumps(e["detail"], sort_keys=True)
            print(f"  {e['ts']:.3f} v{e['version']} {e['event']}: {detail}")
    return 0


def cmd_score(args: argparse.Namespace) -> int:
    """Offline bulk scoring: CSV in -> probabilities out, through the same
    pipelined bucketed dispatch the serving path uses. The batch analog of
    the REST hop for notebook/backfill workflows (the reference would loop
    single Seldon requests; here one command rides score_pipelined).
    Honors CCFD_GRAPH_CR and CCFD_MODEL exactly like `serve`, so a backfill
    scores with the SAME model the REST endpoint serves."""
    import numpy as np

    from ccfd_tpu.config import Config
    from ccfd_tpu.data.ccfd import load_dataset
    from ccfd_tpu.serving.scorer import Scorer

    cfg = Config.from_env()
    if cfg.graph_cr:
        from ccfd_tpu.serving.graph import load_graph_cr

        spec = load_graph_cr(cfg.graph_cr)
        cfg = dataclasses.replace(cfg, model_name=spec.name)
    ds = load_dataset(path=args.input or None)
    # checkpoints hold a model-specific pytree: restore only into the
    # matching model (same guard as `serve`), so backfills score with the
    # SAME params the REST endpoint serves
    if cfg.model_name == "mlp":
        params = _restore_mlp_checkpoint(args.checkpoint_dir)
    elif cfg.model_name == "mlp_q8":
        params = _restore_q8_checkpoint(getattr(args, "quantized_dir", ""))
    elif cfg.model_name == "gbt":
        params = _restore_gbt_params(getattr(args, "gbt_dir", ""))
    else:
        params = None
    scorer = Scorer(
        model_name=cfg.model_name, params=params,
        compute_dtype=cfg.compute_dtype, batch_sizes=cfg.batch_sizes,
    )
    scorer.warmup()
    t0 = time.perf_counter()
    proba = scorer.score_pipelined(ds.X, depth=args.depth)
    elapsed = time.perf_counter() - t0
    if args.output:
        # ccfd-lint: disable=durability-seam -- user-requested CSV export to the path THEY named; not a platform artifact
        with open(args.output, "w") as f:
            f.write("proba_1\n")
            f.write("\n".join(repr(float(p)) for p in proba) + "\n")
    frauds = int((proba >= cfg.fraud_threshold).sum())
    print(json.dumps({
        "rows": int(ds.n),
        "seconds": round(elapsed, 3),
        "tx_s": round(ds.n / max(elapsed, 1e-9), 1),
        "flagged_fraud": frauds,
        "fraud_threshold": cfg.fraud_threshold,
        # 0-row input (e.g. a filtered-to-header CSV): mean of nothing is
        # NaN, which json.dumps would emit as invalid JSON
        "mean_proba": round(float(np.mean(proba)), 6) if ds.n else None,
        "output": args.output or None,
        "checkpoint": bool(params is not None),
    }))
    return 0


def cmd_analyze(args: argparse.Namespace) -> int:
    """Batch analytics report: the notebook workflow the reference runs on
    JupyterHub+Spark (frauddetection_cr.yaml:7-53), as one CLI command."""
    import numpy as np

    from ccfd_tpu.analytics.engine import AnalyticsEngine
    from ccfd_tpu.data.ccfd import FEATURE_NAMES, load_dataset

    ds = load_dataset()
    engine = AnalyticsEngine(nbins=args.nbins)
    report = engine.summarize(ds.X, ds.y)
    out = report.to_dict()
    out["workers"] = engine.mesh.size
    # strongest off-diagonal correlations — what the exploration notebook eyeballs
    corr = report.corr.copy()
    idx = np.triu_indices_from(corr, k=1)
    order = np.argsort(-np.abs(corr[idx]))[: args.top_corr]
    out["top_correlations"] = [
        {
            "a": FEATURE_NAMES[idx[0][k]],
            "b": FEATURE_NAMES[idx[1][k]],
            "corr": float(corr[idx][k]),
        }
        for k in order
    ]
    if args.drift_split:
        half = ds.n // 2
        scores = engine.drift(engine.summarize(ds.X[:half]), ds.X[half:])
        worst = int(np.argmax(scores))
        out["drift_self_check"] = {
            "max_psi": float(scores[worst]),
            "worst_feature": FEATURE_NAMES[worst],
        }
    print(json.dumps(out))
    return 0


def cmd_bench(args: argparse.Namespace) -> int:
    # bench.py lives at the repo root (next to the package), not inside it
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()
    return 0


def cmd_store(args: argparse.Namespace) -> int:
    """Object-store ops: the reference run-book's Ceph/S3 steps
    (README.md:136-343 — serve the store, upload the CSV, `aws s3 ls`)."""
    from ccfd_tpu.config import Config
    from ccfd_tpu.store.client import S3Client
    from ccfd_tpu.store.objectstore import Credentials, ObjectStore
    from ccfd_tpu.store.server import StoreServer

    cfg = Config.from_env()
    creds = Credentials(
        cfg.access_key_id or "ccfd-access", cfg.secret_access_key or "ccfd-secret"
    )
    if args.action == "serve":
        store = ObjectStore(root=args.root)
        store.add_credentials(creds)
        store.create_bucket(cfg.s3_bucket)
        srv = StoreServer(store, host=args.host, port=args.port).start()
        print(json.dumps({"endpoint": srv.endpoint, "bucket": cfg.s3_bucket}))
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            srv.stop()
        return 0

    # explicit --endpoint beats the s3endpoint env var
    client = S3Client(
        args.endpoint or cfg.s3_endpoint or "http://127.0.0.1:9000", creds
    )
    if args.action == "put":
        if args.file:
            with open(args.file, "rb") as f:
                data = f.read()
        else:  # upload the (synthetic or CCFD_CSV) dataset as creditcard.csv
            from ccfd_tpu.data.ccfd import load_dataset, to_csv_bytes

            data = to_csv_bytes(load_dataset())
        client.create_bucket(cfg.s3_bucket)
        client.put(cfg.s3_bucket, cfg.filename, data)
        print(json.dumps({"bucket": cfg.s3_bucket, "key": cfg.filename,
                          "bytes": len(data)}))
    elif args.action == "ls":
        print(json.dumps({"bucket": cfg.s3_bucket,
                          "keys": client.list(cfg.s3_bucket)}))
    return 0


def cmd_manifests(args: argparse.Namespace) -> int:
    """Emit per-service k8s manifests from the platform CR (the reference's
    deploy/*.yaml topology, generated so it can't drift from the spec)."""
    from ccfd_tpu.platform.k8s import write_manifests
    from ccfd_tpu.platform.operator import PlatformSpec

    spec = PlatformSpec.from_yaml(args.file)
    written = write_manifests(spec, args.out)
    print(json.dumps({"written": written}))
    return 0


def cmd_up(args: argparse.Namespace) -> int:
    """Operator entry: CR file -> running platform (the reference run-book
    README.md:44-537 as one command)."""
    from ccfd_tpu.platform.operator import Platform, PlatformSpec

    spec = PlatformSpec.from_yaml(args.file)
    platform = Platform(spec).up()
    print(json.dumps(platform.status(), indent=2), file=sys.stderr)
    try:
        if args.exit_after_producer and not spec.component("producer").enabled:
            print("[up] --exit-after-producer given but producer is disabled "
                  "in the CR", file=sys.stderr)
            platform.down()
            return 2
        _tune_gc()
        if args.exit_after_producer:
            platform.wait_producer(timeout_s=args.drain_s)
            time.sleep(2.0)  # let timers/signals drain
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        for name, reg in platform.registries.items():
            print(f"--- {name} ---", file=sys.stderr)
            print(reg.render(), file=sys.stderr)
        platform.down()
    return 0


def cmd_fleet_member(args: argparse.Namespace) -> int:
    """One fleet member: a full operator Platform from a CR-shaped JSON
    spec file (written by fleet/supervisor.py), sharing the networked bus
    named in its ``bus.url``. Runs until SIGTERM/SIGINT — or SIGKILL,
    which is the point: the fleet drill proves the FLEET survives that."""
    from ccfd_tpu.platform.operator import Platform, PlatformSpec

    with open(args.spec) as f:
        cr = json.load(f)
    platform = Platform(PlatformSpec.from_cr(cr)).up()
    fleet = platform.fleet
    print(json.dumps({
        "member": (fleet.member if fleet is not None else None),
        "heartbeat": (fleet.endpoint if fleet is not None else None),
        "status": platform.status(),
    }, indent=2), file=sys.stderr)
    _tune_gc()
    rc = _serve_forever()
    platform.down()
    return rc


def cmd_fleet_up(args: argparse.Namespace) -> int:
    """Bring up an N-member fleet on this box: one shared bus server
    (embedded unless --bus names one) + N member processes, babysat until
    interrupted. The drill form of this command lives in
    tools/fleet_drill.py (kill/respawn + invariant assertions)."""
    from ccfd_tpu.fleet.supervisor import (
        FleetSupervisor,
        _free_port,
        build_member_cr,
    )

    bus_url = args.bus
    bus_srv = None
    if not bus_url:
        from ccfd_tpu.bus.broker import Broker
        from ccfd_tpu.bus.server import BrokerServer

        broker = Broker(default_partitions=args.partitions)
        bus_srv = BrokerServer(broker)
        port = bus_srv.start("127.0.0.1", 0)
        bus_url = f"http://127.0.0.1:{port}"
        print(f"[fleet] embedded bus on {bus_url}", file=sys.stderr)
    names = [f"m{i:02d}" for i in range(args.members)]
    ports = {n: _free_port() for n in names}
    endpoints = {n: f"http://127.0.0.1:{p}" for n, p in ports.items()}
    sup = FleetSupervisor(bus_url, args.state_dir)
    for n in names:
        sup.add_member(n, build_member_cr(
            n, bus_url, ports[n],
            [endpoints[o] for o in names if o != n],
            args.state_dir,
            ttl_s=args.ttl_s,
            global_max_inflight=args.global_max_inflight,
        ))
        sup.spawn(n)
    try:
        sup.wait_ready(timeout_s=120.0)
        print(json.dumps(sup.status(), indent=2), file=sys.stderr)
        rc = _serve_forever()
    finally:
        sup.stop_all()
        if bus_srv is not None:
            bus_srv.stop()
    return rc


def cmd_fleet_status(args: argparse.Namespace) -> int:
    """Fleet health by heartbeat endpoint: membership, partition
    ownership (with disjointness verdict) and champion parity."""
    from urllib.error import URLError
    from urllib.request import urlopen

    from ccfd_tpu.fleet.member import HEALTH_PATH
    from ccfd_tpu.fleet.protocol import (
        check_disjoint_ownership,
        check_fingerprint_parity,
    )

    health: dict[str, Any] = {}
    for peer in [p.strip() for p in args.peers.split(",") if p.strip()]:
        try:
            with urlopen(peer.rstrip("/") + HEALTH_PATH, timeout=2.0) as r:
                health[peer] = json.loads(r.read().decode())
        except (URLError, OSError, ValueError):
            health[peer] = None
    up = {p: h for p, h in health.items() if h is not None}
    owners = {h["member"]: h.get("partitions", []) for h in up.values()}
    n_partitions = (max((max(ps) for ps in owners.values() if ps),
                        default=-1) + 1)
    doc = {
        "members": health,
        "ownership_violations": check_disjoint_ownership(
            owners, n_partitions),
        "parity": check_fingerprint_parity(
            {h["member"]: h.get("fingerprint") for h in up.values()}),
    }
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        for peer, h in health.items():
            if h is None:
                print(f"{peer}: DOWN")
            else:
                print(f"{peer}: {h['member']} partitions={h.get('partitions')} "
                      f"epoch={h.get('epoch')} "
                      f"quarantined={h.get('quarantined')}")
        print(f"ownership: "
              f"{doc['ownership_violations'] or 'disjoint, all owned'}")
        print(f"parity: {doc['parity']}")
    return 0 if not doc["ownership_violations"] else 1


def _tracing_for(cfg, registry, component):
    """(tracer, sink) for a standalone service role, or (None, None) when
    CCFD_TRACE_SAMPLE=0 turns tracing off. The tracer lands spans in the
    role's SCRAPED registry; the sink's own sampler metrics live in a
    'tracing' registry the caller may also export."""
    if cfg.trace_sample <= 0:
        return None, None
    from ccfd_tpu.metrics.prom import Registry
    from ccfd_tpu.observability.trace import SpanSink, Tracer

    sink = SpanSink(sample=cfg.trace_sample,
                    slow_s=cfg.trace_slow_ms / 1e3, registry=Registry())
    return Tracer(registry, component=component, sink=sink), sink


def _broker_for(cfg, registry=None):
    """BROKER_URL decides the transport: http:// -> RemoteBroker against a
    `bus serve` process; kafka:// -> real-cluster adapter (health counters
    into ``registry`` when given); anything else -> in-process Broker
    (durable when CCFD_BUS_DIR is set)."""
    from ccfd_tpu.bus.client import broker_from_url

    kwargs = (
        {"registry": registry}
        if registry is not None and cfg.broker_url.startswith("kafka://")
        else {}
    )
    remote = broker_from_url(cfg.broker_url, **kwargs)
    if remote is not None:
        return remote
    from ccfd_tpu.bus.broker import Broker

    return Broker(log_dir=cfg.bus_log_dir or None, fsync=cfg.bus_fsync,
                    retention_records=cfg.bus_retention_records or None,
                    retention_overrides=cfg.parsed_retention_overrides())


def _install_sigterm_as_interrupt() -> None:
    """k8s stops pods with SIGTERM (the generated manifests run these
    commands as containers); Python's default handler would kill the
    process without running any of the KeyboardInterrupt cleanup paths
    below (server stop, engine state save). Map SIGTERM to the same
    graceful path SIGINT takes."""
    import signal

    def raise_interrupt(signum, frame):  # noqa: ARG001
        raise KeyboardInterrupt

    try:
        signal.signal(signal.SIGTERM, raise_interrupt)
    except (ValueError, OSError):  # pragma: no cover - non-main thread
        pass


def _serve_forever() -> int:
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


def cmd_bus(args: argparse.Namespace) -> int:
    """Standalone networked broker — the Kafka-cluster role (reference
    deploy/frauddetection_cr.yaml:73-77), durable when --dir is given."""
    from ccfd_tpu.bus.broker import Broker
    from ccfd_tpu.bus.server import BrokerServer

    cfg = Config.from_env()
    log_dir = args.dir or (cfg.bus_log_dir or None)
    broker = Broker(log_dir=log_dir, fsync=cfg.bus_fsync,
                    retention_records=cfg.bus_retention_records or None,
                    retention_overrides=cfg.parsed_retention_overrides())
    from ccfd_tpu.metrics.prom import Registry

    bus_registry = Registry()
    tracer, _sink = _tracing_for(cfg, bus_registry, "bus")
    srv = BrokerServer(broker, registry=bus_registry, tracer=tracer)
    port = srv.start(args.host, args.port)
    print(f"[bus] listening on {args.host}:{port}"
          + (f" (durable: {log_dir})" if log_dir else " (memory)"), file=sys.stderr)
    _tune_gc()
    rc = _serve_forever()
    srv.stop()
    return rc


def cmd_engine(args: argparse.Namespace) -> int:
    """Standalone KIE-shaped engine server (reference ccd-service on :8090)."""
    from ccfd_tpu.process.fraud import build_engine
    from ccfd_tpu.process.server import EngineServer

    cfg = Config.from_env()
    broker = _broker_for(cfg)
    engine = build_engine(cfg, broker)
    if args.state_file:
        import os as _os

        if _os.path.exists(args.state_file):
            engine.load(args.state_file)
    tracer, _sink = _tracing_for(cfg, engine.registry, "kie")
    srv = EngineServer(engine, tracer=tracer)
    port = srv.start(args.host, args.port)
    print(f"[engine] KIE REST on {args.host}:{port} "
          f"definitions={list(engine.definitions())}", file=sys.stderr)
    _tune_gc()
    try:
        while True:
            time.sleep(args.save_interval_s if args.state_file else 3600)
            if args.state_file:
                engine.save(args.state_file)
    except KeyboardInterrupt:
        if args.state_file:
            engine.save(args.state_file)
    srv.stop()
    return 0


def cmd_router(args: argparse.Namespace) -> int:
    """Standalone decision router (reference ccd-fuse): remote bus, remote
    or local scorer (SELDON_URL), remote engine (KIE_SERVER_URL)."""
    from ccfd_tpu.router.router import Router

    cfg = Config.from_env()
    # fail the cheap misconfiguration first: building + warming the local
    # scorer can cost minutes of XLA compilation
    if not cfg.kie_server_url.startswith("http"):
        print("[router] standalone mode needs KIE_SERVER_URL=http://... "
              "(run `python -m ccfd_tpu engine`)", file=sys.stderr)
        return 2
    from ccfd_tpu.metrics.prom import Registry

    router_registry = Registry()
    # the adapter's produce/send-error counters land in the router's
    # scraped registry (the KafkaCluster board's adapter panels)
    broker = _broker_for(cfg, registry=router_registry)
    tracer, trace_sink = _tracing_for(cfg, router_registry, "router")
    # standing fault plan from CCFD_FAULTS (runtime/faults.py): degraded
    # edges are injectable on the standalone role exactly like under the
    # platform operator
    fault_plan = None
    if cfg.faults_spec:
        from ccfd_tpu.runtime.faults import FaultPlan

        fault_plan = FaultPlan.from_string(cfg.faults_spec)
    scorer_faults = (fault_plan.injector("scorer", router_registry)
                     if fault_plan else None)
    host_score_fn = None
    if cfg.seldon_url.startswith("http"):
        from ccfd_tpu.serving.client import SeldonClient

        score_fn = SeldonClient(cfg, faults=scorer_faults,
                                tracer=tracer).score
    else:
        from ccfd_tpu.serving.scorer import Scorer

        scorer = Scorer(model_name=cfg.model_name, compute_dtype=cfg.compute_dtype,
                        batch_sizes=cfg.batch_sizes,
                        dispatch_deadline_ms=cfg.scorer_dispatch_deadline_ms())
        scorer.warmup()
        score_fn = scorer.score
        if scorer_faults is not None:
            score_fn = scorer_faults.wrap_fn(score_fn)
        if scorer.has_host_forward:
            host_score_fn = scorer.host_score
    from ccfd_tpu.process.client import EngineRestClient

    engine = EngineRestClient(cfg.kie_server_url,
                              timeout_s=cfg.seldon_timeout_ms / 1000.0,
                              retries=cfg.client_retries,
                              tracer=tracer)
    if fault_plan is not None:
        inj = fault_plan.injector("engine", router_registry)
        if inj is not None:
            engine = inj.wrap(engine, methods=("start_process",
                                               "start_process_batch",
                                               "signal"))
    # production role: the degradation ladder is on (same default as the
    # platform operator) — a sick scorer edge degrades, never stalls.
    # --workers (or CCFD_ROUTER_WORKERS) fans the loop out partition-
    # parallel with shared coalesced dispatch (router/parallel.py).
    workers = (args.workers if args.workers is not None
               else cfg.router_workers)
    # overload control (runtime/overload.py): same default-on wiring as
    # the platform operator — adaptive AIMD in-flight budget, priority-
    # aware shedding, dispatch watchdog (CCFD_OVERLOAD_* env knobs)
    overload = None
    if cfg.overload_enabled:
        from ccfd_tpu.runtime.overload import OverloadControl

        n_eff = workers if workers > 0 else max(
            1, len(broker.end_offsets(cfg.kafka_topic)))
        overload = OverloadControl.from_config(
            cfg, router_registry, max_batch=4096, workers=n_eff)
    if workers == 1:
        router = Router(cfg, broker, score_fn, engine,
                        registry=router_registry,
                        host_score_fn=host_score_fn, degrade=True,
                        tracer=tracer, overload=overload)
    else:
        from ccfd_tpu.router.parallel import ParallelRouter

        router = ParallelRouter(cfg, broker, score_fn, engine,
                                registry=router_registry, workers=workers,
                                host_score_fn=host_score_fn, degrade=True,
                                tracer=tracer, coalesce=cfg.router_coalesce,
                                overload=overload)
    # the reference scrapes the router on :8091/prometheus
    # (reference README.md:503-507); the standalone role must expose the
    # same surface the generated k8s Service/annotations point at
    from ccfd_tpu.metrics.exporter import MetricsExporter

    regs = {"router": router.registry}
    if trace_sink is not None:
        regs["tracing"] = trace_sink.registry
    exporter = MetricsExporter(
        regs, host="0.0.0.0", port=args.metrics_port, sink=trace_sink,
    ).start()
    print(f"[router] consuming {cfg.kafka_topic!r} from {cfg.broker_url}; "
          f"metrics on :{args.metrics_port}/prometheus", file=sys.stderr)
    _tune_gc()
    try:
        router.run(poll_timeout_s=0.05)
    except KeyboardInterrupt:
        router.close()
    exporter.stop()
    return 0


def cmd_notify(args: argparse.Namespace) -> int:
    """Standalone notification service (reference notification-service)."""
    from ccfd_tpu.notify.service import NotificationService

    cfg = Config.from_env()
    broker = _broker_for(cfg)
    from ccfd_tpu.metrics.prom import Registry

    notify_registry = Registry()
    tracer, trace_sink = _tracing_for(cfg, notify_registry, "notify")
    svc = NotificationService(cfg, broker, notify_registry,
                              reply_prob=args.reply_prob,
                              approve_prob=args.approve_prob, seed=args.seed,
                              tracer=tracer)
    from ccfd_tpu.metrics.exporter import MetricsExporter

    regs = {"notify": svc.registry}
    if trace_sink is not None:
        regs["tracing"] = trace_sink.registry
    exporter = MetricsExporter(
        regs, host="0.0.0.0", port=args.metrics_port, sink=trace_sink,
    ).start()
    print(f"[notify] consuming {cfg.customer_notification_topic!r} from "
          f"{cfg.broker_url}; metrics on :{args.metrics_port}/prometheus",
          file=sys.stderr)
    _tune_gc()
    try:
        svc.run(poll_timeout_s=0.05)
    except KeyboardInterrupt:
        svc.stop()
    exporter.stop()
    return 0


def cmd_investigate(args: argparse.Namespace) -> int:
    """Investigator simulation working the engine's task queue over the
    KIE-shaped REST contract (the demo's Business Central humans,
    reference README.md:547-581) — seeded verdicts, rate-limited, trusts
    confident console pre-fills; the decisions train the user-task
    model."""
    from ccfd_tpu.process.client import EngineRestClient
    from ccfd_tpu.process.investigator import InvestigatorService

    cfg = Config.from_env()
    engine = EngineRestClient(
        args.engine_url or cfg.kie_server_url,
        timeout_s=cfg.seldon_timeout_ms / 1000.0,
        retries=cfg.client_retries,
    )
    svc = InvestigatorService(
        engine, rate_per_s=args.rate, trust_threshold=args.trust,
        base_fraud_rate=args.fraud_rate, seed=args.seed,
    )
    from ccfd_tpu.metrics.exporter import MetricsExporter

    exporter = MetricsExporter(
        {"investigator": svc.registry}, host="0.0.0.0",
        port=args.metrics_port,
    ).start()
    print(f"[investigate] working {args.engine_url or cfg.kie_server_url} "
          f"at <= {args.rate}/s; metrics on :{args.metrics_port}/prometheus",
          file=sys.stderr)
    try:
        svc.run()
    except KeyboardInterrupt:
        svc.stop()
    exporter.stop()
    return 0


def cmd_producer(args: argparse.Namespace) -> int:
    """Standalone transaction producer (reference ProducerDeployment)."""
    from ccfd_tpu.producer.producer import Producer

    cfg = Config.from_env()
    broker = _broker_for(cfg)
    from ccfd_tpu.metrics.prom import Registry

    producer_registry = Registry()
    tracer, _sink = _tracing_for(cfg, producer_registry, "producer")
    producer = Producer(cfg, broker, registry=producer_registry,
                        tracer=tracer)
    n = producer.run(limit=args.limit, rate_per_s=args.rate,
                     wire_format=args.wire_format)
    print(f"[producer] streamed {n} rows to {cfg.producer_topic!r}",
          file=sys.stderr)
    return 0


def cmd_tasks(args: argparse.Namespace) -> int:
    """The investigator's CLI: list and complete user tasks on the engine
    (reference: KIE console user-task workflow, README.md:571-605 /
    docs/images/events-3 — the investigation branch's human decisions).
    Completing with --outcome approved/rejected is exactly the decision
    the user-task prediction model learns from (process/usertask_model)."""
    from ccfd_tpu.process.client import EngineRestClient

    cfg = Config.from_env()
    url = args.engine_url or cfg.kie_server_url
    if not url.startswith("http"):
        print(
            f"[tasks] KIE_SERVER_URL={url!r} is not an http engine endpoint; "
            "start one with `ccfd_tpu engine` and point --engine-url at it",
            file=sys.stderr,
        )
        return 2
    client = EngineRestClient(
        url,
        timeout_s=cfg.seldon_timeout_ms / 1000.0,
        retries=cfg.client_retries,
    )
    if args.complete is not None:
        # the engine's completion payload is the boolean is_fraud verdict
        # (fraud.py task_outcome gateway: truthy => cancel the transaction);
        # the CLI speaks the investigator's words and maps them explicitly —
        # passing the raw string through would make "approved" truthy and
        # CANCEL the transaction
        verdicts = {"approved": False, "rejected": True,
                    "false": False, "true": True}
        if args.outcome is None or args.outcome.lower() not in verdicts:
            print(
                "[tasks] --complete requires --outcome approved|rejected "
                "(approved = legitimate transaction, rejected = confirmed "
                "fraud)",
                file=sys.stderr,
            )
            return 2
        is_fraud = verdicts[args.outcome.lower()]
        try:
            client.complete_task(args.complete, is_fraud)
        except (RuntimeError, OSError) as e:
            print(f"[tasks] engine error: {e}", file=sys.stderr)
            return 2
        print(json.dumps({"completed": args.complete,
                          "outcome": args.outcome.lower(),
                          "is_fraud": is_fraud}))
        return 0
    try:
        views = client.tasks(args.status)
    except (RuntimeError, OSError) as e:
        print(f"[tasks] engine error: {e}", file=sys.stderr)
        return 2
    print(json.dumps({"status": args.status, "count": len(views),
                      "tasks": views}))
    return 0


def cmd_loadgen(args: argparse.Namespace) -> int:
    """Benchmark a RUNNING scorer endpoint (local or remote) with the same
    lean client the in-tree bench uses, so operator numbers compare
    directly against BASELINE.md's rest section. Exits non-zero when any
    request errored — usable as a smoke gate in deploy pipelines."""
    from ccfd_tpu.utils.loadgen import run_loadgen

    cfg = Config.from_env()
    report = run_loadgen(
        args.url, clients=args.clients, rows_per_request=args.rows,
        seconds=args.seconds, path=args.path, token=cfg.seldon_token,
    )
    print(json.dumps(report))
    return 0 if report["errors"] == 0 and report["failed_clients"] == 0 else 3


def cmd_doctor(args: argparse.Namespace) -> int:
    """One-shot operational health report, built for the failure mode this
    stack actually sees: an accelerator attachment that wedges so hard
    ``jax.devices()`` never returns. Everything that could hang runs in a
    SUBPROCESS with a timeout; the report is one JSON object on stdout and
    the exit code is 0 only when the accelerator answered.

    Sections: accelerator (platform, device count, measured dispatch RTT),
    native toolchain, bus/store reachability for the configured URLs,
    checkpoint presence, and the env-contract values in effect.
    """
    import subprocess

    cfg = Config.from_env()
    report: dict[str, Any] = {"ok": True}

    # --- accelerator (subprocess probe + tiny-dispatch RTT) ---------------
    probe_code = (
        "import json, os, time, jax\n"
        # operator-exported JAX_PLATFORMS wins over the site hook, same
        # contract as _honor_platform_env
        "w = os.environ.get('JAX_PLATFORMS', '')\n"
        "w and jax.config.update('jax_platforms', w)\n"
        "d = jax.devices()\n"
        "import jax.numpy as jnp\n"
        "x = jnp.zeros((16, 30), jnp.float32)\n"
        "(x @ x.T).block_until_ready()  # compile\n"
        "t0 = time.perf_counter()\n"
        "for _ in range(5): (x @ x.T).block_until_ready()\n"
        "rtt_ms = (time.perf_counter() - t0) / 5 * 1e3\n"
        "print(json.dumps({'platform': jax.default_backend(),"
        " 'devices': len(d), 'dispatch_rtt_ms': round(rtt_ms, 3)}))\n"
    )
    t0 = time.perf_counter()
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe_code],
            timeout=args.probe_s, capture_output=True, text=True,
        )
        if r.returncode == 0 and r.stdout.strip():
            report["accelerator"] = json.loads(r.stdout.strip().splitlines()[-1])
            report["accelerator"]["probe_s"] = round(
                time.perf_counter() - t0, 2
            )
        else:
            report["accelerator"] = {
                "error": (r.stderr or "probe failed").strip()[-300:],
            }
            report["ok"] = False
    except subprocess.TimeoutExpired:
        report["accelerator"] = {
            "error": f"WEDGED: no answer within {args.probe_s:.0f}s "
            "(jax.devices() hang — the attachment is stuck; serving falls "
            "back to the host tier, see serving/dispatch.py)",
        }
        report["ok"] = False

    # --- native toolchain -------------------------------------------------
    try:
        from ccfd_tpu.native import native_available

        report["native_toolchain"] = bool(native_available())
    except Exception as e:  # noqa: BLE001 - report, don't crash the doctor
        report["native_toolchain"] = f"error: {e}"

    # --- bus / store reachability (only for networked URLs) ---------------
    def _tcp_check(url: str) -> str:
        import socket
        from urllib.parse import urlparse

        if not url.startswith(("http://", "https://", "kafka://")):
            return "in-process (nothing to dial)"
        p = urlparse(url)
        # scheme-correct default ports: 9092 is Kafka's, not HTTP's
        port = p.port or {
            "kafka": 9092, "https": 443
        }.get(p.scheme, 80)
        try:
            with socket.create_connection((p.hostname, port), timeout=3):
                return "reachable"
        except OSError as e:
            return f"unreachable: {e}"

    report["bus"] = {"url": cfg.broker_url, "status": _tcp_check(cfg.broker_url)}
    if cfg.s3_endpoint:
        report["store"] = {
            "url": cfg.s3_endpoint, "status": _tcp_check(cfg.s3_endpoint),
        }

    # --- model artifacts --------------------------------------------------
    from ccfd_tpu.parallel.checkpoint import CheckpointManager

    for label, d in (("checkpoint", args.checkpoint_dir),
                     ("quantized", args.quantized_dir)):
        try:
            step = CheckpointManager(d).latest_step()
        except Exception:  # noqa: BLE001 - unreadable dir reads as absent
            step = None
        report[label] = {"dir": d, "latest_step": step}
    report["gbt"] = {
        "dir": _GBT_DIR,
        "present": os.path.exists(os.path.join(_GBT_DIR, "params.npz")),
    }

    # --- config in effect -------------------------------------------------
    report["config"] = {
        "model": cfg.model_name,
        "compute_dtype": cfg.compute_dtype,
        "fraud_threshold": cfg.fraud_threshold,
        "seldon_timeout_ms": cfg.seldon_timeout_ms,
        "dispatch_deadline_ms": cfg.dispatch_deadline_ms,
        # the resolved value serving would arm (-1 above = auto). Computed
        # from the SUBPROCESS probe's platform — Config's own helper calls
        # jax.default_backend(), which would initialize a backend in THIS
        # process and hang on the exact wedge the doctor diagnoses
        "dispatch_deadline_ms_effective": (
            cfg.dispatch_deadline_ms
            if cfg.dispatch_deadline_ms >= 0
            else (
                f"unknown (probe failed; accelerator backends arm "
                f"{cfg.seldon_timeout_ms})"
                if "platform" not in report["accelerator"]
                else (
                    0.0
                    if report["accelerator"]["platform"] == "cpu"
                    else float(cfg.seldon_timeout_ms)
                )
            )
        ),
        "host_tier_rows": cfg.host_tier_rows,
        "batch_sizes": list(cfg.batch_sizes),
    }
    print(json.dumps(report))
    return 0 if report["ok"] else 3


def cmd_lint(args: argparse.Namespace) -> int:
    """``ccfd_tpu lint``: the repo's review-finding invariants as a
    machine-checked gate (analysis/ — AST rules + suppression pragmas +
    baseline). Exit 0 only when every finding is fixed, suppressed with
    an inline justification, or grandfathered in the baseline. Stays
    jax-free: the gate must run before (and regardless of) any
    accelerator bring-up."""
    from ccfd_tpu.analysis import core as lint_core

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = os.path.join(root, "tools", "lint_baseline.json")
    if args.write_baseline and args.rules:
        # a subset run sees only that subset's findings; writing them out
        # would silently DROP every other rule's grandfathered entries
        print("[lint] --write-baseline regenerates the FULL baseline; "
              "combining it with --rules would drop the other rules' "
              "entries", file=sys.stderr)
        return 2
    try:
        report = lint_core.run_lint(
            root,
            paths=args.paths or None,
            # --write-baseline must see EVERY finding, including ones the
            # current baseline already grandfathers — filtering first
            # would empty the baseline on the second consecutive run
            baseline_path=(None if (args.no_baseline or args.write_baseline)
                           else baseline_path),
            rule_names=args.rules.split(",") if args.rules else None,
        )
    except ValueError as e:  # unknown rule, bad target, malformed baseline
        print(f"[lint] {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        lint_core.write_baseline(baseline_path, report.findings)
        print(f"[lint] wrote {len(report.findings)} finding(s) to "
              f"{baseline_path}", file=sys.stderr)
        return 0
    if args.json:
        print(json.dumps(report.to_json(), indent=1, sort_keys=True))
    else:
        for line in report.human_lines():
            print(line)
    return report.exit_code


def _tune_gc() -> None:
    """Service processes amortize gc over large gen-0 batches: jax's gc
    callback runs XLA garbage collection on EVERY Python collection, and
    the hot loops' record churn fires gen-0 hundreds of times per second
    at the default threshold — measured +51% pipeline throughput on the
    1-core host (utils/gctune.py; CCFD_GC_THRESHOLD=0 opts out)."""
    from ccfd_tpu.utils.gctune import tune_for_service

    tune_for_service()


def _honor_platform_env() -> None:
    """A site hook may force its own jax platform (e.g. a TPU tunnel plugin)
    over the environment; an operator who exported JAX_PLATFORMS explicitly
    wins — services must not hang dialing an unavailable accelerator when
    told to run on CPU."""
    import os

    want = os.environ.get("JAX_PLATFORMS", "")
    if want:
        try:
            import jax

            jax.config.update("jax_platforms", want)
        except Exception:  # pragma: no cover - jax absent/odd build
            pass


def _probe_backend_or_fallback() -> None:
    """Bound CLI startup against a wedged accelerator attachment.

    The TPU tunnel can wedge so hard that ``jax.devices()`` blocks forever —
    before any Scorer (whose own dispatch deadline can't help yet) exists.
    Probe the default backend in a SUBPROCESS with a timeout (the same
    discipline bench.py uses); on a dead probe, force CPU and say so, rather
    than hanging `train`/`serve`/`router` bring-up indefinitely. Operators
    opt out with CCFD_NO_PROBE=1 (e.g. to wait out a flaky attachment) and
    tune the timeout with CCFD_PROBE_S."""
    import os
    import subprocess

    if os.environ.get("CCFD_NO_PROBE") or os.environ.get("JAX_PLATFORMS"):
        return  # explicit platform choice already bounded/bypassed the dial
    timeout_s = float(os.environ.get("CCFD_PROBE_S", "45"))
    # a healthy probe is cached briefly so back-to-back CLI invocations on
    # a healthy attachment don't pay accelerator bring-up twice per call
    cache = os.path.join(
        os.path.expanduser("~"), ".cache", "ccfd_tpu", "probe_ok"
    )
    ttl_s = float(os.environ.get("CCFD_PROBE_CACHE_S", "300"))
    try:
        import time as _time

        # ccfd-lint: disable=monotonic-durations -- age vs a file MTIME is wall-clock math by definition; a backwards step just re-probes early
        if ttl_s > 0 and _time.time() - os.path.getmtime(cache) < ttl_s:
            return
    except OSError:
        pass
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True,
        )
        if r.returncode == 0:
            try:
                os.makedirs(os.path.dirname(cache), exist_ok=True)
                # ccfd-lint: disable=durability-seam -- zero-byte mtime marker; losing it costs one re-probe
                with open(cache, "w"):
                    pass
                os.utime(cache, None)
            except OSError:
                pass
            return
    except (subprocess.SubprocessError, OSError):
        pass
    print(
        f"[ccfd_tpu] accelerator probe failed within {timeout_s:.0f}s "
        "(wedged attachment?); falling back to CPU — set CCFD_NO_PROBE=1 "
        "to wait for the accelerator instead",
        file=sys.stderr,
    )
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - jax absent/odd build
        pass


# commands whose code path imports jax; the others (bus, notify, producer,
# store, engine) stay jax-free and must not pay the import at startup
_JAX_CMDS = {"demo", "serve", "train", "analyze", "bench", "router", "up",
             "score", "quantize", "fleet"}


_SERVICE_CMDS = {"serve", "bus", "engine", "router", "notify", "store", "up",
                 "fleet", "replay"}


def main(argv: list[str] | None = None) -> int:
    args_list = list(sys.argv[1:] if argv is None else argv)
    if args_list and args_list[0] in _JAX_CMDS:
        _honor_platform_env()
        _probe_backend_or_fallback()
        # persistent XLA compilation cache: service restarts and repeat
        # runs skip the 20-40s-per-shape first compile on the TPU tunnel
        from ccfd_tpu.utils.compile_cache import enable as _enable_cache

        _enable_cache()
    if args_list and args_list[0] in _SERVICE_CMDS:
        _install_sigterm_as_interrupt()
    p = argparse.ArgumentParser(prog="ccfd_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("demo", help="run the full pipeline in-process")
    d.add_argument("--transactions", type=int, default=2000)
    d.add_argument("--rate", type=float, default=None)
    d.add_argument("--train-steps", type=int, default=200)
    d.add_argument("--reply-timeout", type=float, default=2.0)
    d.add_argument("--drain-s", type=float, default=30.0)
    d.add_argument("--wire-format", choices=("dict", "csv"), default="dict")
    d.add_argument("--seed", type=int, default=0)
    d.set_defaults(fn=cmd_demo)

    s = sub.add_parser(
        "serve", help="REST prediction server (Seldon contract)",
        description="Model selection is CCFD_MODEL (config.py). Decided "
        "defaults (measured, ENSEMBLE_r04.json): `mlp` for THROUGHPUT "
        "(the MXU path), `logreg`/modelfull for RANKING QUALITY (held-out "
        "AUC 0.9638 vs 0.9484 — and the validation-selected ensemble "
        "blend weight is w_mlp=0.0, i.e. blending the MLP into the "
        "linear model does not improve ranking on the canonical table; "
        "the graph CR remains the multi-node serving surface, not a "
        "quality upgrade).",
    )
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--train", action="store_true", help="train before serving")
    s.add_argument("--train-steps", type=int, default=300)
    s.add_argument("--checkpoint-dir", default="./checkpoints",
                   help="serve the newest `train` checkpoint when present")
    s.add_argument("--quantized-dir", default=_Q8_DIR,
                   help="int8 checkpoint dir used when CCFD_MODEL=mlp_q8")
    s.add_argument("--gbt-dir", default=_GBT_DIR,
                   help="tree params dir used when CCFD_MODEL=gbt "
                        "(written by `train --family hgb`)")
    s.set_defaults(fn=cmd_serve)

    t = sub.add_parser(
        "train",
        help="offline-train the flagship MLP (or --family hgb for the "
             "servable HistGradientBoosting tree ensemble)",
    )
    t.add_argument("--steps", type=int, default=500)
    t.add_argument("--checkpoint-dir", default="./checkpoints")
    t.add_argument("--family", choices=("mlp", "hgb"), default="mlp",
                   help="hgb: sklearn HistGradientBoosting (bounded depth) "
                        "-> served gbt params; quality-tied with logreg at "
                        "0.9641 held-out (HGB_SERVABLE_r04.json)")
    t.add_argument("--hgb-depth", type=int, default=8,
                   help="max tree depth for --family hgb (the dense "
                        "embedding is 2^depth nodes/tree)")
    t.add_argument("--gbt-dir", default=_GBT_DIR,
                   help="output dir for --family hgb params")
    t.add_argument("--from-store", action="store_true",
                   help="fetch creditcard.csv from the object store "
                        "(the reference's S3 data path)")
    t.add_argument("--store-url", default="",
                   help="store endpoint (default: s3endpoint env)")
    t.add_argument("--test-frac", type=float, default=0.2)
    t.set_defaults(fn=cmd_train)

    q = sub.add_parser(
        "quantize", help="int8-quantize the newest train checkpoint (mlp_q8)"
    )
    q.add_argument("--checkpoint-dir", default="./checkpoints")
    q.add_argument("--out-dir", default=_Q8_DIR)
    q.add_argument("--test-frac", type=float, default=0.2)
    q.set_defaults(fn=cmd_quantize)

    au = sub.add_parser(
        "audit",
        help="reconstruct one decision by tx id (decision provenance "
             "plane), or tail the engine's audit event stream",
    )
    au.add_argument("tx_id", nargs="?", default=None,
                    help="transaction id (or partition:offset uid) to "
                    "reconstruct; omit to tail the engine audit stream")
    au.add_argument("--dir", default="",
                    help="audit log dir (default: CCFD_AUDIT_DIR)")
    au.add_argument("--lifecycle-dir", default="",
                    help="lifecycle state dir for the lineage join "
                    "(default: CCFD_LIFECYCLE_DIR)")
    au.add_argument("--incident-dir", default="",
                    help="incident bundle dir for the incident join "
                    "(default: CCFD_INCIDENT_DIR)")
    au.add_argument("--url", default="",
                    help="live exporter endpoint: fetch the record, "
                    "incident bundle and kept trace over HTTP instead "
                    "of (or in addition to) the on-disk artifacts")
    au.add_argument("--json", action="store_true",
                    help="emit the full reconstruction document as JSON")
    au.add_argument("--topic", default="", help="default: CCFD_AUDIT_TOPIC")
    au.add_argument("--group", default="audit-tail",
                    help="consumer group (offsets persist per group)")
    au.add_argument("--follow", action="store_true", help="keep consuming")
    au.add_argument("--limit", type=int, default=0, help="stop after N events")
    au.set_defaults(fn=cmd_audit)

    rp = sub.add_parser(
        "replay",
        help="bulk replay & backtest: re-score a recorded audit window "
             "with verdict-parity conservation (replay plane)",
    )
    rp.add_argument("--dir", default="",
                    help="audit log dir holding the recorded window "
                    "(default: CCFD_AUDIT_DIR)")
    rp.add_argument("--since-seq", type=int, default=None,
                    help="window start (DecisionRecord seq, inclusive)")
    rp.add_argument("--until-seq", type=int, default=None,
                    help="window end (DecisionRecord seq, inclusive)")
    rp.add_argument("--from-incident", default="",
                    help="incident bundle JSON: re-drive the decisions "
                    "in flight across the breach window")
    rp.add_argument("--what-if-threshold", type=float, default=None,
                    help="host-side backtest: which recorded decisions "
                    "flip under this FRAUD_THRESHOLD (never touches the "
                    "live path)")
    rp.add_argument("--live", action="store_true",
                    help="bring the platform up and re-produce the window "
                    "through the live serving path under bulk admission")
    rp.add_argument("--cr", default="",
                    help="CR file for --live (default: a minimal replay "
                    "platform over --dir)")
    rp.add_argument("--state-dir", default="",
                    help="durable replay-cursor dir (default: "
                    "CCFD_REPLAY_DIR)")
    rp.add_argument("--window-id", default="",
                    help="explicit window id (cursor key; default: the "
                    "seq range)")
    rp.add_argument("--no-resume", action="store_true",
                    help="ignore an existing cursor and restart the "
                    "window from its first row")
    rp.add_argument("--json", action="store_true",
                    help="emit the full report (bounded findings "
                    "included) as JSON")
    rp.set_defaults(fn=cmd_replay)

    lc = sub.add_parser(
        "lifecycle",
        help="model-lifecycle lineage + audit trail (versions console)",
    )
    lc.add_argument("--dir", default="",
                    help="lifecycle state dir (default: CCFD_LIFECYCLE_DIR)")
    lc.add_argument("--audit", action="store_true",
                    help="print the transition audit trail too")
    lc.add_argument("--version", type=int, default=0,
                    help="restrict the audit trail to one version id")
    lc.add_argument("--json", action="store_true",
                    help="emit the full lineage+audit as JSON")
    lc.set_defaults(fn=cmd_lifecycle)

    sc = sub.add_parser("score", help="offline bulk scoring: CSV -> probabilities")
    sc.add_argument("--input", default="", help="creditcard.csv path (default: CCFD_CSV/synthetic)")
    sc.add_argument("--output", default="", help="write proba_1 CSV here")
    sc.add_argument("--depth", type=int, default=2, help="pipelined dispatch depth")
    sc.add_argument("--checkpoint-dir", default="./checkpoints")
    sc.add_argument("--quantized-dir", default=_Q8_DIR,
                    help="int8 checkpoint dir used when CCFD_MODEL=mlp_q8")
    sc.add_argument("--gbt-dir", default=_GBT_DIR,
                    help="tree params dir used when CCFD_MODEL=gbt")
    sc.set_defaults(fn=cmd_score)

    an = sub.add_parser("analyze", help="dataset analytics report (Spark/notebook analog)")
    an.add_argument("--nbins", type=int, default=32)
    an.add_argument("--top-corr", type=int, default=8)
    an.add_argument("--drift-split", action="store_true",
                    help="also run a first-half vs second-half drift self-check")
    an.set_defaults(fn=cmd_analyze)

    b = sub.add_parser("bench", help="print the benchmark JSON line")
    b.set_defaults(fn=cmd_bench)

    st = sub.add_parser("store", help="S3-shaped object store (serve/put/ls)")
    st.add_argument("action", choices=("serve", "put", "ls"))
    st.add_argument("--root", default=None, help="persistence dir (serve)")
    st.add_argument("--host", default="127.0.0.1")
    st.add_argument("--port", type=int, default=9000)
    st.add_argument("--endpoint", default=None,
                    help="store endpoint (overrides s3endpoint env)")
    st.add_argument("--file", default=None, help="local file to upload (put)")
    st.set_defaults(fn=cmd_store)

    bus = sub.add_parser("bus", help="networked broker (Kafka-cluster role)")
    bus.add_argument("--host", default="0.0.0.0")
    bus.add_argument("--port", type=int, default=9092)
    bus.add_argument("--dir", default=None, help="durable segment-log dir")
    bus.set_defaults(fn=cmd_bus)

    en = sub.add_parser("engine", help="KIE-shaped process engine server")
    en.add_argument("--host", default="0.0.0.0")
    en.add_argument("--port", type=int, default=8090)
    en.add_argument("--state-file", default=None)
    en.add_argument("--save-interval-s", type=float, default=5.0)
    en.set_defaults(fn=cmd_engine)

    ro = sub.add_parser("router", help="standalone decision router")
    ro.add_argument("--metrics-port", type=int, default=8091)  # README.md:503-507
    ro.add_argument("--workers", type=int, default=None,
                    help="partition-parallel worker loops sharing one "
                    "coalesced scorer dispatch (default: "
                    "CCFD_ROUTER_WORKERS; 1 = single router, 0 = one "
                    "worker per bus partition)")
    ro.set_defaults(fn=cmd_router)

    no = sub.add_parser("notify", help="standalone notification service")
    no.add_argument("--reply-prob", type=float, default=0.8)
    no.add_argument("--approve-prob", type=float, default=0.7)
    no.add_argument("--seed", type=int, default=0)
    no.add_argument("--metrics-port", type=int, default=8080)
    no.set_defaults(fn=cmd_notify)

    inv = sub.add_parser(
        "investigate",
        help="investigator simulation over the KIE REST contract",
    )
    inv.add_argument("--engine-url", default="",
                     help="engine REST base (default: KIE_SERVER_URL)")
    inv.add_argument("--rate", type=float, default=50.0,
                     help="max task completions per second")
    inv.add_argument("--trust", type=float, default=0.9,
                     help="follow the console pre-fill at/above this "
                          "prediction confidence")
    inv.add_argument("--fraud-rate", type=float, default=0.05,
                     help="independent-verdict fraud probability")
    inv.add_argument("--seed", type=int, default=0)
    inv.add_argument("--metrics-port", type=int, default=8082)
    inv.set_defaults(fn=cmd_investigate)

    pr = sub.add_parser("producer", help="standalone transaction producer")
    pr.add_argument("--limit", type=int, default=None)
    pr.add_argument("--rate", type=float, default=None)
    pr.add_argument("--wire-format", choices=("dict", "csv"), default="csv")
    pr.set_defaults(fn=cmd_producer)

    mf = sub.add_parser("manifests", help="emit k8s manifests from the CR")
    mf.add_argument("-f", "--file", default="deploy/platform_cr.yaml")
    mf.add_argument("-o", "--out", default="deploy/k8s")
    mf.set_defaults(fn=cmd_manifests)

    u = sub.add_parser("up", help="bring up the platform from a CR file")
    u.add_argument("-f", "--file", default="deploy/platform_cr.yaml")
    u.add_argument("--exit-after-producer", action="store_true")
    u.add_argument("--drain-s", type=float, default=120.0)
    u.set_defaults(fn=cmd_up)

    fl = sub.add_parser(
        "fleet",
        help="multi-host fleet: N operator processes over one shared bus "
             "(membership, admission shares, champion parity; fleet/)",
    )
    flsub = fl.add_subparsers(dest="action", required=True)
    flm = flsub.add_parser(
        "member", help="run ONE fleet member from a CR-shaped JSON spec "
                       "(normally exec'd by the fleet supervisor)")
    flm.add_argument("--spec", required=True,
                     help="member spec file (fleet/supervisor.py "
                          "build_member_cr shape)")
    flm.set_defaults(fn=cmd_fleet_member)
    flu = flsub.add_parser(
        "up", help="spawn an N-member fleet (embedded bus unless --bus)")
    flu.add_argument("--members", type=int, default=2)
    flu.add_argument("--bus", default="",
                     help="shared bus URL (default: start an embedded "
                          "bus server on a free port)")
    flu.add_argument("--state-dir", default="./fleet-state")
    flu.add_argument("--partitions", type=int, default=4,
                     help="tx-topic partitions for the embedded bus")
    flu.add_argument("--ttl-s", type=float, default=3.0,
                     help="membership lease")
    flu.add_argument("--global-max-inflight", type=int, default=0,
                     help="fleet-wide admission ceiling (0 = per-member "
                          "budgets stand alone)")
    flu.set_defaults(fn=cmd_fleet_up)
    fls = flsub.add_parser(
        "status", help="fleet health by peer heartbeat endpoints")
    fls.add_argument("--peers", required=True,
                     help="comma-separated heartbeat endpoints")
    fls.add_argument("--json", action="store_true")
    fls.set_defaults(fn=cmd_fleet_status)

    tk = sub.add_parser(
        "tasks", help="investigator workflow: list/complete engine user tasks"
    )
    tk.add_argument("--engine-url", default="",
                    help="engine REST base (default: KIE_SERVER_URL)")
    tk.add_argument("--status", default="open")
    tk.add_argument("--complete", type=int, default=None, metavar="TASK_ID")
    tk.add_argument("--outcome", default=None,
                    help="approved | rejected (with --complete)")
    tk.set_defaults(fn=cmd_tasks)

    lg = sub.add_parser(
        "loadgen", help="drive a deployed scorer's REST endpoint (JSON report)"
    )
    lg.add_argument("--url", default="http://127.0.0.1:8000")
    lg.add_argument("--clients", type=int, default=8)
    lg.add_argument("--rows", type=int, default=16)
    lg.add_argument("--seconds", type=float, default=10.0)
    lg.add_argument("--path", default=None,
                    help="request path (default: the URL's own path, else "
                         "/api/v0.1/predictions)")
    lg.set_defaults(fn=cmd_loadgen)

    li = sub.add_parser(
        "lint",
        help="AST invariant checker over ccfd_tpu/ (review findings as "
             "machine-checked rules; see analysis/)",
    )
    li.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: ccfd_tpu/)")
    li.add_argument("--root", default="",
                    help="repo root (default: the installed package's "
                         "parent)")
    li.add_argument("--json", action="store_true",
                    help="strict-JSON report instead of human lines")
    li.add_argument("--rules", default="",
                    help="comma-separated rule subset (default: all)")
    li.add_argument("--baseline", default=None,
                    help="baseline file (default: tools/lint_baseline.json)")
    li.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    li.add_argument("--write-baseline", action="store_true",
                    help="grandfather the current findings into the "
                         "baseline file")
    li.set_defaults(fn=cmd_lint)

    dr = sub.add_parser(
        "doctor", help="environment/attachment health report (JSON)"
    )
    dr.add_argument("--probe-s", type=float, default=30.0,
                    help="accelerator probe timeout (subprocess)")
    dr.add_argument("--checkpoint-dir", default="./checkpoints")
    dr.add_argument("--quantized-dir", default=_Q8_DIR)
    dr.set_defaults(fn=cmd_doctor)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
