"""Persistent XLA compilation cache for every jax entry point.

On the TPU attachment a first compile costs ~20-40s per (executable,
shape) — the scorer's bucket set alone is several of those, paid again on
every service restart, bench run, and retrain bring-up. JAX's persistent
compilation cache keeps compiled executables on disk keyed by HLO +
compile options + platform, so only the FIRST process ever pays.

``enable()`` is called by the CLI for jax-using commands and by bench.py;
CCFD_COMPILE_CACHE overrides the location, ``0``/``off`` disables. On the
CPU backend it is OFF unless explicitly pointed at a directory — XLA:CPU
reload of persisted executables is unsafe (see ``enable``). Failures
(read-only fs, old jax) degrade silently to no caching — the cache is an
optimization, never a requirement.
"""

from __future__ import annotations

import hashlib
import os
import platform


def _host_fingerprint() -> str:
    """Short stable id for this host's CPU. XLA:CPU persists AOT machine
    code compiled for the build host's exact feature set; loading it on a
    host with different features risks SIGILL (cpu_aot_loader warns about
    exactly this). Keying the cache dir by CPU identity makes a different
    host start clean instead of loading incompatible artifacts. TPU
    executables are unaffected either way — same-host reruns (the case the
    cache exists for) still hit."""
    material = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    material += line
                    break
    except OSError:
        material += platform.processor()
    return hashlib.sha256(material.encode()).hexdigest()[:12]


def enable(path: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache; returns the
    directory in use, or None when disabled/unavailable.

    On the CPU backend the cache defaults OFF unless an explicit ``path``
    or CCFD_COMPILE_CACHE directory opts in: XLA:CPU's cache RELOAD is not
    trustworthy. Beyond the cross-host SIGILL risk above, reloading a
    donated multi-device executable written by a previous process can
    return one that computes garbage — observed with the 8-virtual-device
    sharded train step, which reloads to a deterministically wrong loss on
    its first step and scribbled donated buffers after. CPU compiles cost
    seconds; the cache exists for the 20-40s-per-shape TPU tunnel
    compiles, where executables are serialized protos, not AOT machine
    code.
    """
    env = os.environ.get("CCFD_COMPILE_CACHE", "")
    if env.strip().lower() in ("0", "off", "false", "no"):
        return None
    try:
        import jax

        if path is None and not env.strip() and jax.default_backend() == "cpu":
            return None
        base = path or env or os.path.join(
            os.path.expanduser("~"), ".cache", "ccfd_tpu", "xla"
        )
        # fingerprint under overridden bases too: a shared
        # CCFD_COMPILE_CACHE on a heterogeneous fleet is exactly where
        # cross-host AOT reuse bites
        target = os.path.join(base, _host_fingerprint())
        os.makedirs(target, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", target)
        # cache even quick compiles: the tunnel round trip dominates, and
        # the scorer's small buckets compile fast but re-run often
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        return target
    except Exception:  # noqa: BLE001 - optimization only, never required
        return None
