"""Persistent XLA compilation cache for every jax entry point.

On the TPU attachment a first compile costs ~20-40s per (executable,
shape) — the scorer's bucket set alone is several of those, paid again on
every service restart, bench run, and retrain bring-up. JAX's persistent
compilation cache keeps compiled executables on disk keyed by HLO +
compile options + platform, so only the FIRST process ever pays.

``enable()`` is called by the CLI for jax-using commands and by bench.py;
CCFD_COMPILE_CACHE overrides the location, ``0``/``off`` disables.
Failures (read-only fs, old jax) degrade silently to no caching — the
cache is an optimization, never a requirement.
"""

from __future__ import annotations

import os


def enable(path: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache; returns the
    directory in use, or None when disabled/unavailable."""
    env = os.environ.get("CCFD_COMPILE_CACHE", "")
    if env.strip().lower() in ("0", "off", "false", "no"):
        return None
    target = path or env or os.path.join(
        os.path.expanduser("~"), ".cache", "ccfd_tpu", "xla"
    )
    try:
        os.makedirs(target, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", target)
        # cache even quick compiles: the tunnel round trip dominates, and
        # the scorer's small buckets compile fast but re-run often
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        return target
    except Exception:  # noqa: BLE001 - optimization only, never required
        return None
