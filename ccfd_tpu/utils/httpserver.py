"""Shared HTTP server base for every service surface in the framework.

``ThreadingHTTPServer``'s socketserver default listen backlog
(``request_queue_size``) is 5: a burst of concurrent clients — exactly the
load the dynamic batcher exists to coalesce, or N components dialing the
bus at bring-up — overflows the accept queue and gets connection resets.
One subclass fixes it for every server (serving, engine, bus, store,
metrics, health).
"""

from __future__ import annotations

from http.server import ThreadingHTTPServer


class FrameworkHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 256
