"""Standalone REST load generator for a deployed scorer.

The reference's users benchmark their Seldon endpoint with external load
tools; this is the in-tree equivalent, tuned for honest numbers on small
hosts: clients are SUBPROCESSES (in-process threads would share the GIL
with whatever else runs on the box and pollute the p99 with client-side
scheduling), each client is a raw socket + pre-serialized request bytes
(an http.client loop burns hundreds of µs/request on header objects),
and latency is measured send-to-full-response per request.

``_CLIENT`` is the single copy of that client — bench.py's ``rest``
section runs the same script, so ``ccfd_tpu loadgen`` numbers compare
directly against BASELINE.md. It handles real-deployment HTTP, not just
the in-tree server: Content-Length and chunked responses, servers or
proxies that close the connection per response (reconnect + retry), and
non-200s counted as errors rather than dying.

CLI: ``ccfd_tpu loadgen --url http://host:8000 --clients 8 --rows 16``.
The bearer token travels via the child's environment (CCFD_LOADGEN_TOKEN),
never argv — argv is world-readable in /proc on shared hosts.
"""
from __future__ import annotations

import json
import subprocess
import sys
from typing import Any

_CLIENT = r"""
import json, os, socket, sys, time
host, port, path, rows_n, seconds = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], int(sys.argv[4]),
    float(sys.argv[5]),
)
token = os.environ.get("CCFD_LOADGEN_TOKEN", "")
row = [float(j % 7) for j in range(30)]
payload = json.dumps({"data": {"ndarray": [row] * rows_n}}).encode()
auth = b"Authorization: Bearer " + token.encode() + b"\r\n" if token else b""
req = (b"POST " + path.encode() + b" HTTP/1.1\r\n"
       b"Host: " + host.encode() + b"\r\n"
       b"Content-Type: application/json\r\n" + auth +
       b"Content-Length: " + str(len(payload)).encode() + b"\r\n\r\n" + payload)


def connect():
    s = socket.create_connection((host, port), timeout=10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def read_response(sock, buf):
    '''Consume one response from sock; returns (status_ok, rest, closed).
    Handles Content-Length, chunked, and close-delimited bodies.'''
    while True:
        head_end = buf.find(b"\r\n\r\n")
        if head_end >= 0:
            break
        chunk = sock.recv(1 << 16)
        if not chunk:
            return None, b"", True  # closed before a full header
        buf += chunk
    head = buf[:head_end].lower()
    ok = buf.startswith(b"HTTP/1.1 200") or buf.startswith(b"HTTP/1.0 200")
    will_close = b"connection: close" in head or buf.startswith(b"HTTP/1.0")
    body_start = head_end + 4
    if b"content-length:" in head:
        cl = int(head.split(b"content-length:", 1)[1].split(b"\r\n", 1)[0])
        while len(buf) < body_start + cl:
            chunk = sock.recv(1 << 16)
            if not chunk:
                return ok, b"", True
            buf += chunk
        return ok, buf[body_start + cl:], will_close
    if b"transfer-encoding:" in head and b"chunked" in head.split(
        b"transfer-encoding:", 1
    )[1].split(b"\r\n", 1)[0]:
        rest = buf[body_start:]
        while True:
            line_end = rest.find(b"\r\n")
            while line_end < 0:
                chunk = sock.recv(1 << 16)
                if not chunk:
                    return ok, b"", True
                rest += chunk
                line_end = rest.find(b"\r\n")
            # chunk extensions ("1a;name=val") are legal; size is the part
            # before any ';'
            size = int(rest[:line_end].split(b";")[0], 16)
            if size == 0:
                # the zero chunk may be followed by trailer headers; the
                # body ends at the blank line either way
                term = rest.find(b"\r\n\r\n", line_end)
                while term < 0:
                    chunk = sock.recv(1 << 16)
                    if not chunk:
                        return ok, b"", True
                    rest += chunk
                    term = rest.find(b"\r\n\r\n", line_end)
                return ok, rest[term + 4:], will_close
            need = line_end + 2 + size + 2
            while len(rest) < need:
                chunk = sock.recv(1 << 16)
                if not chunk:
                    return ok, b"", True
                rest += chunk
            rest = rest[need:]
    # neither: body is delimited by connection close
    while True:
        chunk = sock.recv(1 << 16)
        if not chunk:
            return ok, b"", True
        buf += chunk


sock = connect()
lat, errors, attempts = [], 0, 0
buf = b""
stop_at = time.perf_counter() + seconds
t_loop = time.perf_counter()
while time.perf_counter() < stop_at:
    t1 = time.perf_counter()
    attempts += 1
    try:
        sock.sendall(req)
        ok, buf, closed = read_response(sock, buf)
    except OSError:
        ok, closed = None, True
    if ok is None:
        # connection died mid-request (per-response-close server, proxy
        # recycling): reconnect and retry this request once
        try:
            sock.close()
        except OSError:
            pass
        sock = connect()
        buf = b""
        try:
            sock.sendall(req)
            ok, buf, closed = read_response(sock, buf)
        except OSError:
            ok, closed = False, True
    if ok is False or ok is None:
        # non-200/failed: count it, but keep it OUT of the latency sample
        # — throughput and percentiles describe SUCCESSFUL requests only,
        # so a run with many errors can't report healthy-looking numbers
        errors += 1
    else:
        lat.append((time.perf_counter() - t1) * 1e3)
    if closed:
        try:
            sock.close()
        except OSError:
            pass
        sock = connect()
        buf = b""
print(json.dumps({"lat": lat, "errors": errors, "attempts": attempts,
                  "loop_s": time.perf_counter() - t_loop}))
"""


def run_loadgen(
    url: str,
    clients: int = 8,
    rows_per_request: int = 16,
    seconds: float = 10.0,
    path: str | None = None,
    token: str = "",
) -> dict[str, Any]:
    """Drive ``url`` with ``clients`` subprocess clients; returns the
    aggregate report (requests_s, tx_s, p50/p99 ms, errors). The URL's own
    path is honored when ``path`` is not given; all client subprocesses are
    killed on any error so a wedged endpoint can't leave orphans hammering
    it."""
    import os
    from urllib.parse import urlparse

    import numpy as np

    p = urlparse(url if "//" in url else "//" + url)
    host = p.hostname or "127.0.0.1"
    port = p.port or (443 if p.scheme == "https" else 80)
    if p.scheme == "https":
        raise ValueError("loadgen speaks plain HTTP (the serving contract)")
    if path is None:
        path = p.path if p.path and p.path != "/" else "/api/v0.1/predictions"
    env = dict(os.environ)
    if token:
        env["CCFD_LOADGEN_TOKEN"] = token  # env, not argv: /proc is public
    else:
        env.pop("CCFD_LOADGEN_TOKEN", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CLIENT, host, str(port), path,
             str(rows_per_request), str(seconds)],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        for _ in range(clients)
    ]
    lat: list[float] = []
    errors = 0
    attempts = 0
    loop_s = 0.0
    failed = 0
    try:
        for pr in procs:
            try:
                out, _ = pr.communicate(timeout=seconds + 60)
            except subprocess.TimeoutExpired:
                failed += 1
                continue
            if pr.returncode != 0 or not out.strip():
                failed += 1
                continue
            try:
                rep = json.loads(out.strip().splitlines()[-1])
            except (ValueError, IndexError):
                failed += 1
                continue
            lat.extend(rep["lat"])
            errors += rep["errors"]
            attempts += rep.get("attempts", len(rep["lat"]))
            loop_s = max(loop_s, rep["loop_s"])
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.kill()
    if not lat:
        if attempts:
            # every request errored (e.g. the model answers 500 for all):
            # that is a REPORT, not a client failure — surface the counts
            # that diagnose it instead of a misleading traceback
            return {
                "url": url,
                "clients": clients,
                "rows_per_request": rows_per_request,
                "seconds": round(loop_s, 2),
                "requests_s": 0.0,
                "attempts_s": round(attempts / max(loop_s, 1e-9), 1),
                "tx_s": 0.0,
                "p50_ms": None,
                "p99_ms": None,
                "errors": errors,
                "failed_clients": failed,
            }
        raise RuntimeError(f"no client produced results ({failed} failed)")
    lat_a = np.asarray(lat)
    # successful requests only: the clients exclude errored/retried
    # attempts from the latency sample, so requests_s/tx_s/percentiles
    # can't look healthy while the error counter climbs
    n_req = len(lat)
    return {
        "url": url,
        "clients": clients,
        "rows_per_request": rows_per_request,
        "seconds": round(loop_s, 2),
        "requests_s": round(n_req / loop_s, 1),
        "attempts_s": round(attempts / loop_s, 1),
        "tx_s": round(n_req * rows_per_request / loop_s, 1),
        "p50_ms": round(float(np.percentile(lat_a, 50)), 3),
        "p99_ms": round(float(np.percentile(lat_a, 99)), 3),
        "errors": errors,
        "failed_clients": failed,
    }
