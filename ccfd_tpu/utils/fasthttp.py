"""Minimal HTTP/1.1 server for the serving hot path.

``BaseHTTPRequestHandler`` costs ~1 ms per request on the predict hop —
readline-based parsing plus an ``email``-module header parse per request —
which is most of the REST latency budget once scoring itself is fast
(BASELINE.json: p99 < 10 ms end-to-end). This server keeps the same
threading model (one daemon thread per connection, keep-alive) but parses
requests directly off the socket buffer: request line + headers in one
``partition``/``split`` pass, ~10x less per-request overhead.

Deliberately NOT a general web server: no chunked transfer encoding, no
multipart, no TLS, no pipelining guarantees beyond sequential keep-alive —
the framework's four fixed JSON routes (serving, engine, bus, store,
metrics) need none of those. Anything unparseable gets 400 and the
connection closed.

Handler contract: ``handler(method: str, path: str, headers:
dict[bytes, bytes], body: bytes) -> (status: int, content_type: str,
body: bytes)`` — or a 4-tuple with a trailing ``{header: value}`` dict of
extra response headers (the overload plane's 429s carry ``Retry-After``
this way). Header names arrive lowercased.
"""

from __future__ import annotations

import socket
import threading
from typing import Callable

Handler = Callable[[str, str, dict, bytes], tuple[int, str, bytes]]

_REASONS = {
    200: b"OK", 201: b"Created", 400: b"Bad Request", 401: b"Unauthorized",
    404: b"Not Found", 405: b"Method Not Allowed", 413: b"Payload Too Large",
    429: b"Too Many Requests", 500: b"Internal Server Error",
    503: b"Service Unavailable",
}
_MAX_HEAD = 64 * 1024
_MAX_BODY = 256 * 1024 * 1024


class FastHTTPServer:
    def __init__(
        self,
        address: tuple[str, int],
        handler: Handler,
        name: str = "ccfd-fasthttp",
        backlog: int = 256,
    ):
        self._handler = handler
        self._name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(backlog)
        self.server_address = self._sock.getsockname()
        self._stopping = threading.Event()
        self._accept_thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FastHTTPServer":
        t = threading.Thread(target=self._accept_loop, daemon=True, name=self._name)
        t.start()
        self._accept_thread = t
        return self

    def serve_forever(self) -> None:  # drop-in for the stdlib server surface
        self._accept_loop()

    def shutdown(self) -> None:
        self._stopping.set()
        try:
            # poke the accept loop awake so it observes the stop flag
            with socket.create_connection(
                ("127.0.0.1", self.server_address[1]), timeout=1.0
            ):
                pass
        except OSError:
            pass

    def server_close(self) -> None:
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    # -- internals ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed
            if self._stopping.is_set():
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(
                target=self._serve_connection, args=(conn,), daemon=True,
                name=f"{self._name}-conn",
            ).start()

    def _serve_connection(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        buf = b""
        try:
            while not self._stopping.is_set():
                # --- read the request head ---
                while b"\r\n\r\n" not in buf:
                    if len(buf) > _MAX_HEAD:
                        self._respond(conn, 400, "text/plain", b"head too large")
                        return
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                head, _, buf = buf.partition(b"\r\n\r\n")
                lines = head.split(b"\r\n")
                parts = lines[0].split(b" ")
                if len(parts) < 2:
                    self._respond(conn, 400, "text/plain", b"bad request line")
                    return
                method = parts[0].decode("latin-1")
                path = parts[1].decode("latin-1")
                headers: dict[bytes, bytes] = {}
                for ln in lines[1:]:
                    k, sep, v = ln.partition(b":")
                    if sep:
                        headers[k.strip().lower()] = v.strip()
                # --- read the body ---
                try:
                    clen = int(headers.get(b"content-length", b"0") or b"0")
                except ValueError:
                    self._respond(conn, 400, "text/plain", b"bad content-length")
                    return
                if clen > _MAX_BODY:
                    self._respond(conn, 413, "text/plain", b"body too large")
                    return
                while len(buf) < clen:
                    chunk = conn.recv(min(1 << 20, clen - len(buf) + 65536))
                    if not chunk:
                        return
                    buf += chunk
                body, buf = buf[:clen], buf[clen:]
                # --- dispatch ---
                extra = None
                try:
                    res = self._handler(method, path, headers, body)
                    status, ctype, resp = res[0], res[1], res[2]
                    if len(res) > 3:  # optional extra response headers
                        extra = res[3]
                except Exception:  # noqa: BLE001 - a handler bug 500s the
                    # request; it must not kill the connection thread silently
                    status, ctype, resp = 500, "text/plain", b"internal error"
                close = headers.get(b"connection", b"").lower() == b"close"
                self._respond(conn, status, ctype, resp, close=close,
                              extra=extra)
                if close:
                    return
        except OSError:
            return  # peer went away mid-request: nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _respond(
        conn: socket.socket, status: int, ctype: str, body: bytes,
        close: bool = False, extra: dict | None = None,
    ) -> None:
        more = b""
        if extra:
            more = b"".join(
                b"\r\n%s: %s" % (str(k).encode("latin-1"),
                                 str(v).encode("latin-1"))
                for k, v in extra.items()
            )
        head = b"HTTP/1.1 %d %s\r\nContent-Type: %s\r\nContent-Length: %d%s%s\r\n\r\n" % (
            status,
            _REASONS.get(status, b"OK"),
            ctype.encode("latin-1"),
            len(body),
            more,
            b"\r\nConnection: close" if close else b"",
        )
        conn.sendall(head + body)
