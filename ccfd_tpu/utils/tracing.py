"""DEPRECATED: moved to :mod:`ccfd_tpu.observability.trace`.

The old module-global ``Tracer`` wrote spans into a private registry the
metrics exporter never served — fixed by the observability subsystem,
where component tracers are registry-injected by the platform operator
and finished spans feed the tail-sampling :class:`SpanSink`. This shim
keeps the historical import path (``Tracer``, ``trace_span``) working;
new code should import from ``ccfd_tpu.observability.trace``.
"""

from __future__ import annotations

import warnings

from ccfd_tpu.observability.trace import (  # noqa: F401 - re-exports
    SpanContext,
    SpanSink,
    Tracer,
    trace_span,
)

warnings.warn(
    "ccfd_tpu.utils.tracing is deprecated; import from "
    "ccfd_tpu.observability.trace",
    DeprecationWarning,
    stacklevel=2,
)
