"""Shared pooled JSON-over-HTTP client for the framework's REST hops.

One implementation of the connection-pool + bounded-retry machinery used by
every service client (engine REST, networked bus): the reference wires its
services the same way — pooled HTTP with `SELDON_POOL_SIZE`-style knobs
(reference README.md:389-393).

Retry policy: idempotent requests retry on any transport error. A
non-idempotent request (process start, produce) retries ONLY on failures
that prove the server cannot have processed it: a refused connection, or
an error raised while SENDING the request (``conn.request`` dying on a
stale pooled keep-alive with BrokenPipe/ConnectionReset — the request was
never completely written, so an incomplete HTTP message is all the server
could have seen and it will not dispatch it). A failure while READING the
response (timeout, reset after the request was fully sent) may mean the
server processed it, and re-sending would duplicate the side effect — no
retry there.

Resilience hooks (runtime/breaker.py, runtime/faults.py): an optional
per-edge ``CircuitBreaker`` gates every request (open circuit ⇒ instant
:class:`~ccfd_tpu.runtime.breaker.CircuitOpenError`, no connection dialed,
no timeout eaten) and records transport errors and 5xx responses as
failures; retries back off exponentially with jitter under an optional
deadline budget instead of hammering a restarting server back-to-back; an
optional ``FaultInjector`` perturbs each attempt so chaos drills exercise
this exact code path.
"""

from __future__ import annotations

import http.client
import json
import queue
import random
import socket
import time
import urllib.parse
from typing import Any


class _NodelayHTTPConnection(http.client.HTTPConnection):
    """http.client sends headers and body as separate segments; with Nagle
    on, a delayed ACK from the server stalls the body ~40 ms. Every client
    hop in the framework disables Nagle (servers do too — see
    utils/httpserver.py)."""

    def connect(self) -> None:
        super().connect()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover
            pass


class PooledHTTPClient:
    def __init__(
        self,
        base_url: str,
        default_port: int,
        pool_size: int = 4,
        timeout_s: float = 5.0,
        retries: int = 2,
        scheme_error: str = "unsupported scheme",
        breaker=None,
        faults=None,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        retry_budget_s: float | None = None,
        tracer=None,
        trace_edge: str = "http",
    ):
        u = urllib.parse.urlparse(base_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"{scheme_error}: {base_url!r}")
        self.host = u.hostname or "localhost"
        self.port = u.port or default_port
        self._timeout = timeout_s
        self._retries = max(0, retries)
        self._breaker = breaker           # runtime/breaker.CircuitBreaker
        self._faults = faults             # runtime/faults.FaultInjector
        self._tracer = tracer             # observability/trace.Tracer
        self._trace_edge = trace_edge     # span name suffix: rpc.<edge>
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._retry_budget_s = retry_budget_s
        self._rng = random.Random(0)      # deterministic backoff jitter
        self._pool: "queue.Queue[http.client.HTTPConnection]" = queue.Queue()
        for _ in range(max(1, pool_size)):
            self._pool.put(self._connect())

    def _connect(self) -> http.client.HTTPConnection:
        return _NodelayHTTPConnection(self.host, self.port, timeout=self._timeout)

    def request(
        self, method: str, path: str, body: Any = None, idempotent: bool = True
    ) -> tuple[int, Any]:
        """-> (status, parsed JSON body or None). Raises ConnectionError when
        the server stays unreachable (or a non-idempotent send failed after
        possibly reaching it); CircuitOpenError (a ConnectionError) when the
        edge's breaker refuses without dialing.

        With a tracer wired, the whole call (retries included) is one
        client span ``rpc.<edge>`` and the span's W3C ``traceparent`` rides
        the request headers, so the server side resumes the same trace. A
        breaker refusal flags the span (``breaker_open``) — the tail
        sampler always keeps those traces."""
        if self._tracer is None:
            return self._do_request(method, path, body, idempotent, None)
        with self._tracer.span(
            f"rpc.{self._trace_edge}",
            attrs={"method": method, "path": path,
                   "peer": f"{self.host}:{self.port}"},
        ) as sp:
            from ccfd_tpu.observability.trace import format_traceparent

            try:
                status, parsed = self._do_request(
                    method, path, body, idempotent,
                    format_traceparent(sp.context))
            except ConnectionError as e:
                from ccfd_tpu.runtime.breaker import CircuitOpenError

                if isinstance(e, CircuitOpenError):
                    sp.attrs["breaker_open"] = True
                raise
            sp.attrs["status"] = status
            if status >= 500:
                # a 5xx is a failed call even though it returns normally:
                # the tail sampler's always-keep-errored rule must see it
                sp.status = "error"
            return status, parsed

    def _do_request(
        self, method: str, path: str, body: Any, idempotent: bool,
        traceparent: str | None,
    ) -> tuple[int, Any]:
        # encode BEFORE the breaker gate: an unencodable body raising
        # after allow() would leak the admitted HALF_OPEN probe slot
        # (nothing would ever record its outcome) and wedge the circuit
        payload = json.dumps(body).encode() if body is not None else None
        req_headers = {"Content-Type": "application/json"}
        if traceparent is not None:
            req_headers["traceparent"] = traceparent
        if self._breaker is not None and not self._breaker.allow():
            from ccfd_tpu.runtime.breaker import CircuitOpenError

            raise CircuitOpenError(
                f"circuit open for {self.host}:{self.port}")
        last: Exception | None = None
        deadline = (None if self._retry_budget_s is None
                    else time.monotonic() + self._retry_budget_s)
        for attempt in range(self._retries + 1):
            conn = self._pool.get()
            sent = False
            returned = False
            t0 = time.monotonic()
            try:
                corrupt = (self._faults.before()
                           if self._faults is not None else False)
                conn.request(method, path, body=payload, headers=req_headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
                self._pool.put(conn)
                returned = True
                parsed = json.loads(data) if data else None
                if self._faults is not None:
                    # a corrupt response raises InjectedFault (an OSError):
                    # the retry/breaker path below treats it like a real
                    # undecodable body
                    parsed = self._faults.after(parsed, corrupt)
                if self._breaker is not None:
                    lat = time.monotonic() - t0
                    if resp.status >= 500:
                        # the server answered but is failing: 5xx counts
                        # toward opening the circuit, the response still
                        # reaches the caller
                        self._breaker.record_failure(lat)
                    else:
                        self._breaker.record_success(lat)
                return resp.status, parsed
            except ValueError as e:
                # undecodable response body from a live server: propagate
                # (historical behavior), but the gated call must still
                # record an outcome — a silent non-record would leak the
                # HALF_OPEN probe slot and wedge the circuit open forever
                if self._breaker is not None:
                    self._breaker.record_failure(time.monotonic() - t0)
                raise
            except (OSError, http.client.HTTPException) as e:
                last = e
                if not returned:
                    conn.close()
                    self._pool.put(self._connect())
                if self._breaker is not None:
                    self._breaker.record_failure(time.monotonic() - t0)
                # send-phase failures (conn.request raised — including a
                # refused connect — mean the request was never fully written,
                # so the server can't have dispatched it) are safe to retry
                # even for non-idempotent requests
                if not idempotent and sent:
                    break
                if attempt < self._retries:
                    from ccfd_tpu.runtime.breaker import backoff_s

                    pause = backoff_s(attempt, self._backoff_base_s,
                                      self._backoff_max_s, self._rng)
                    if (deadline is not None
                            and time.monotonic() + pause > deadline):
                        break  # the budget is spent: fail now, not later
                    time.sleep(pause)
        raise ConnectionError(f"{self.host}:{self.port} unreachable: {last}")

    def close(self) -> None:
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                return
