"""Shared utilities. Tracer/trace_span re-export from their new home
(observability/trace.py) for back-compat — importing the old
``ccfd_tpu.utils.tracing`` module directly warns DeprecationWarning."""

from ccfd_tpu.observability.trace import Tracer, trace_span  # noqa: F401
