"""Sequence fraud scorer: a transformer over per-customer transaction history.

A new model family beyond the reference's single-row classifiers: each
scoring decision sees the customer's recent transaction *history*
(B, L, 30) and predicts fraud for the latest transaction. This is the
long-context member of the model zoo — histories shard over the mesh's
sequence axis and attention runs as ring attention
(ccfd_tpu/ops/ring_attention.py) when L exceeds one chip's comfort.

TPU-first choices: d_model/heads sized to 128-lane multiples, bf16 matmuls
with f32 accumulation, pre-norm blocks, sinusoidal positions (no trainable
position table to shard), last-token readout (streaming scoring semantics:
"given the history, how suspicious is the newest transaction?").
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ccfd_tpu.data.ccfd import NUM_FEATURES
from ccfd_tpu.ops.ring_attention import reference_attention

Params = Mapping[str, Any]

D_MODEL = 128
N_HEADS = 4
N_BLOCKS = 2
MLP_MULT = 4


def init(
    key: jax.Array,
    num_features: int = NUM_FEATURES,
    d_model: int = D_MODEL,
    n_blocks: int = N_BLOCKS,
) -> Params:
    keys = jax.random.split(key, 2 + 4 * n_blocks)
    k = iter(range(len(keys)))

    def dense(kk, fan_in, shape):
        return jax.random.normal(keys[kk], shape, jnp.float32) * jnp.sqrt(1.0 / fan_in)

    blocks = []
    for _ in range(n_blocks):
        blocks.append(
            {
                "ln1": {"scale": jnp.ones((d_model,)), "bias": jnp.zeros((d_model,))},
                "qkv": {"w": dense(next(k), d_model, (d_model, 3 * d_model)),
                        "b": jnp.zeros((3 * d_model,))},
                "proj": {"w": dense(next(k), d_model, (d_model, d_model)),
                         "b": jnp.zeros((d_model,))},
                "ln2": {"scale": jnp.ones((d_model,)), "bias": jnp.zeros((d_model,))},
                "mlp_in": {"w": dense(next(k), d_model, (d_model, MLP_MULT * d_model)),
                           "b": jnp.zeros((MLP_MULT * d_model,))},
                "mlp_out": {"w": dense(next(k), MLP_MULT * d_model,
                                       (MLP_MULT * d_model, d_model)),
                            "b": jnp.zeros((d_model,))},
            }
        )
    return {
        "norm": {
            "mu": jnp.zeros((num_features,), jnp.float32),
            "sigma": jnp.ones((num_features,), jnp.float32),
        },
        "embed": {"w": dense(next(k), num_features, (num_features, d_model)),
                  "b": jnp.zeros((d_model,))},
        "blocks": blocks,
        "head": {
            "ln": {"scale": jnp.ones((d_model,)), "bias": jnp.zeros((d_model,))},
            "w": dense(next(k), d_model, (d_model, 1)),
            "b": jnp.zeros((1,)),
        },
    }


def set_normalizer(params: Params, mean: np.ndarray, std: np.ndarray) -> Params:
    sigma = np.where(np.asarray(std) == 0.0, 1.0, np.asarray(std))
    out = dict(params)
    out["norm"] = {
        "mu": jnp.asarray(mean, jnp.float32),
        "sigma": jnp.asarray(sigma, jnp.float32),
    }
    return out


def _layer_norm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-6) * scale + bias).astype(x.dtype)


def _positions(length: int, d_model: int) -> jax.Array:
    pos = jnp.arange(length)[:, None].astype(jnp.float32)
    dim = jnp.arange(d_model // 2)[None, :].astype(jnp.float32)
    freq = jnp.exp(-jnp.log(10000.0) * 2.0 * dim / d_model)
    angles = pos * freq
    return jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=-1)


def logits(
    params: Params,
    x: jax.Array,
    compute_dtype=jnp.bfloat16,
    attention_fn: Callable[..., jax.Array] | None = None,
    n_heads: int = N_HEADS,
) -> jax.Array:
    """(B, L, F) -> (B,) fraud logit for the last transaction in each history."""
    attn = attention_fn or reference_attention
    mu = jax.lax.stop_gradient(params["norm"]["mu"])
    sigma = jax.lax.stop_gradient(params["norm"]["sigma"])
    h = ((x - mu) / sigma).astype(compute_dtype)
    h = jnp.einsum("blf,fd->bld", h, params["embed"]["w"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    h = (h + params["embed"]["b"]).astype(compute_dtype)
    batch, length, d_model = h.shape
    h = h + _positions(length, d_model).astype(compute_dtype)[None]

    head_dim = d_model // n_heads
    for blk in params["blocks"]:
        z = _layer_norm(h, blk["ln1"]["scale"], blk["ln1"]["bias"])
        qkv = jnp.einsum("bld,de->ble", z, blk["qkv"]["w"].astype(compute_dtype),
                         preferred_element_type=jnp.float32)
        qkv = (qkv + blk["qkv"]["b"]).astype(compute_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(batch, length, n_heads, head_dim).transpose(0, 2, 1, 3)

        a = attn(heads(q), heads(k), heads(v))  # (B, H, L, Dh)
        a = a.transpose(0, 2, 1, 3).reshape(batch, length, d_model)
        a = jnp.einsum("bld,de->ble", a.astype(compute_dtype),
                       blk["proj"]["w"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
        h = h + (a + blk["proj"]["b"]).astype(compute_dtype)

        z = _layer_norm(h, blk["ln2"]["scale"], blk["ln2"]["bias"])
        m = jnp.einsum("bld,de->ble", z, blk["mlp_in"]["w"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
        m = jax.nn.gelu((m + blk["mlp_in"]["b"]).astype(jnp.float32)).astype(compute_dtype)
        m = jnp.einsum("ble,ed->bld", m, blk["mlp_out"]["w"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
        h = h + (m + blk["mlp_out"]["b"]).astype(compute_dtype)

    last = h[:, -1, :]
    last = _layer_norm(last, params["head"]["ln"]["scale"], params["head"]["ln"]["bias"])
    z = jnp.einsum("bd,do->bo", last.astype(compute_dtype),
                   params["head"]["w"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    return (z + params["head"]["b"]).reshape(batch)


@partial(jax.jit, static_argnames=("compute_dtype",))
def apply(params: Params, x: jax.Array, compute_dtype=jnp.bfloat16) -> jax.Array:
    """(B, L, F) -> (B,) proba_1 for the newest transaction."""
    return jax.nn.sigmoid(logits(params, x, compute_dtype))


def logits_readout(
    params: Params,
    x: jax.Array,
    compute_dtype=jnp.bfloat16,
    attention_fn: Callable[..., jax.Array] | None = None,
    n_heads: int = N_HEADS,
    pos_length: int | None = None,
) -> jax.Array:
    """Serving-path ``logits``: the LAST block computes only the readout
    token's output.

    Only position L-1 survives past the final block (``logits`` takes
    ``h[:, -1, :]``), so the last block's q-projection, attention scores,
    proj and MLP are needed for ONE position — its K/V (and every earlier
    block, whose outputs all feed the last block's attention) still run
    over the full sequence. Same params, same math, same numbers modulo
    float reassociation (parity asserted in tests/test_seq.py); the
    saving is the last block's O(L) proj+MLP work, the dominant per-token
    cost at serving time (~1.6x at n_blocks=2).

    ``pos_length``: anchor positional encodings as the LAST ``L`` rows of
    a ``pos_length``-long table. The serving L-bucket ladder dispatches a
    short window ``hist[:, -lb:]`` of a length-``pos_length`` history;
    under the full-L path (zero left-pad) the real tokens sit at
    positions ``pos_length-f .. pos_length-1``, so the short executable
    must give them the SAME encodings — without this, a customer's
    tokens would shift position at every ladder crossover. ``None``
    (default) anchors at ``x``'s own length — identical to ``logits``.
    """
    attn = attention_fn or reference_attention
    mu = jax.lax.stop_gradient(params["norm"]["mu"])
    sigma = jax.lax.stop_gradient(params["norm"]["sigma"])
    h = ((x - mu) / sigma).astype(compute_dtype)
    h = jnp.einsum("blf,fd->bld", h, params["embed"]["w"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    h = (h + params["embed"]["b"]).astype(compute_dtype)
    batch, length, d_model = h.shape
    pos = _positions(pos_length or length, d_model)[-length:]
    h = h + pos.astype(compute_dtype)[None]
    head_dim = d_model // n_heads

    def heads(t, lq):
        return t.reshape(batch, lq, n_heads, head_dim).transpose(0, 2, 1, 3)

    blocks = params["blocks"]
    for blk in blocks[:-1]:
        z = _layer_norm(h, blk["ln1"]["scale"], blk["ln1"]["bias"])
        qkv = jnp.einsum("bld,de->ble", z, blk["qkv"]["w"].astype(compute_dtype),
                         preferred_element_type=jnp.float32)
        qkv = (qkv + blk["qkv"]["b"]).astype(compute_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        a = attn(heads(q, length), heads(k, length), heads(v, length))
        a = a.transpose(0, 2, 1, 3).reshape(batch, length, d_model)
        a = jnp.einsum("bld,de->ble", a.astype(compute_dtype),
                       blk["proj"]["w"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
        h = h + (a + blk["proj"]["b"]).astype(compute_dtype)
        z = _layer_norm(h, blk["ln2"]["scale"], blk["ln2"]["bias"])
        m = jnp.einsum("bld,de->ble", z, blk["mlp_in"]["w"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
        m = jax.nn.gelu((m + blk["mlp_in"]["b"]).astype(jnp.float32)).astype(compute_dtype)
        m = jnp.einsum("ble,ed->bld", m, blk["mlp_out"]["w"].astype(compute_dtype),
                       preferred_element_type=jnp.float32)
        h = h + (m + blk["mlp_out"]["b"]).astype(compute_dtype)

    # last block: K/V over the full sequence, everything else readout-only
    blk = blocks[-1]
    z = _layer_norm(h, blk["ln1"]["scale"], blk["ln1"]["bias"])
    w_qkv = blk["qkv"]["w"].astype(compute_dtype)
    b_qkv = blk["qkv"]["b"]
    kv = jnp.einsum("bld,de->ble", z, w_qkv[:, d_model:],
                    preferred_element_type=jnp.float32)
    kv = (kv + b_qkv[d_model:]).astype(compute_dtype)
    k, v = jnp.split(kv, 2, axis=-1)
    q = jnp.einsum("bld,de->ble", z[:, -1:, :], w_qkv[:, :d_model],
                   preferred_element_type=jnp.float32)
    q = (q + b_qkv[:d_model]).astype(compute_dtype)
    a = attn(heads(q, 1), heads(k, length), heads(v, length))  # (B, H, 1, Dh)
    a = a.transpose(0, 2, 1, 3).reshape(batch, 1, d_model)
    a = jnp.einsum("bld,de->ble", a.astype(compute_dtype),
                   blk["proj"]["w"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    hl = h[:, -1:, :] + (a + blk["proj"]["b"]).astype(compute_dtype)
    z = _layer_norm(hl, blk["ln2"]["scale"], blk["ln2"]["bias"])
    m = jnp.einsum("bld,de->ble", z, blk["mlp_in"]["w"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    m = jax.nn.gelu((m + blk["mlp_in"]["b"]).astype(jnp.float32)).astype(compute_dtype)
    m = jnp.einsum("ble,ed->bld", m, blk["mlp_out"]["w"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    hl = hl + (m + blk["mlp_out"]["b"]).astype(compute_dtype)

    last = hl[:, 0, :]
    last = _layer_norm(last, params["head"]["ln"]["scale"], params["head"]["ln"]["bias"])
    z = jnp.einsum("bd,do->bo", last.astype(compute_dtype),
                   params["head"]["w"].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    return (z + params["head"]["b"]).reshape(batch)


@partial(jax.jit, static_argnames=("compute_dtype", "pos_length"))
def apply_serving(params: Params, x: jax.Array,
                  compute_dtype=jnp.bfloat16,
                  pos_length: int | None = None) -> jax.Array:
    """Serving twin of :func:`apply` built on :func:`logits_readout` —
    what :class:`~ccfd_tpu.serving.history.SeqScorer` dispatches
    (``pos_length`` = the store's full L, so short L-bucket windows keep
    full-path positional encodings)."""
    return jax.nn.sigmoid(
        logits_readout(params, x, compute_dtype, pos_length=pos_length))


def loss_fn(params: Params, x: jax.Array, y: jax.Array,
            pos_weight: float = 8.0, compute_dtype=jnp.bfloat16,
            attention_fn=None) -> jax.Array:
    from ccfd_tpu.models.losses import weighted_bce_from_logits

    z = logits(params, x, compute_dtype, attention_fn=attention_fn)
    return weighted_bce_from_logits(z, y, pos_weight)
