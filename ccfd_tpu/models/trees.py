"""Gradient-boosted tree ensemble re-expressed as tensorized XLA evaluation.

BASELINE.json configs[1]: "XGBoost / GBT fraud classifier re-expressed as JAX
inference". A CPU tree library walks pointers per row; that shape is hostile
to TPU. Here every tree is embedded into a *complete* binary tree of static
depth D stored as three dense arrays

    feature   (T, 2^D - 1) int32   — split feature id per internal node
    threshold (T, 2^D - 1) float32 — split threshold per internal node
    leaf      (T, 2^D)     float32 — leaf values (learning rate folded in)

and a batch descends all T trees in lockstep with D vectorized gather steps
(heap layout: children of node i are 2i+1 / 2i+2). D is recovered from the
leaf-array shape, so the Python loop unrolls statically under ``jit`` — no
data-dependent control flow, no host sync, pure VPU gathers + one reduce.

Sparse/unbalanced source trees (e.g. fitted sklearn estimators) embed by
propagating early leaves to every descendant leaf slot, which preserves exact
semantics while keeping the dense layout.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

Params = Mapping[str, Any]


def num_internal(depth: int) -> int:
    return (1 << depth) - 1


def init_empty(n_trees: int, depth: int, base: float = 0.0) -> Params:
    """All-zero ensemble (every tree returns 0) — useful as a starting point."""
    return {
        "feature": jnp.zeros((n_trees, num_internal(depth)), jnp.int32),
        "threshold": jnp.full((n_trees, num_internal(depth)), jnp.inf, jnp.float32),
        "leaf": jnp.zeros((n_trees, 1 << depth), jnp.float32),
        "base": jnp.asarray(base, jnp.float32),
    }


def depth_of(params: Params) -> int:
    return int(params["leaf"].shape[-1]).bit_length() - 1


def logits(params: Params, x: jax.Array) -> jax.Array:
    """(B, F) -> (B,) raw ensemble scores (base + sum of leaf values)."""
    feat, thr, leaf = params["feature"], params["threshold"], params["leaf"]
    n_trees = leaf.shape[0]
    depth = depth_of(params)
    batch = x.shape[0]
    tree_ids = jnp.arange(n_trees)[None, :]  # (1, T) broadcasts over batch
    idx = jnp.zeros((batch, n_trees), jnp.int32)
    for _ in range(depth):
        node_feat = feat[tree_ids, idx]  # (B, T)
        node_thr = thr[tree_ids, idx]
        xv = jnp.take_along_axis(x[:, None, :], node_feat[:, :, None], axis=2)[..., 0]
        go_right = (xv > node_thr).astype(jnp.int32)
        idx = 2 * idx + 1 + go_right
    leaf_idx = idx - num_internal(depth)
    return params["base"] + leaf[tree_ids, leaf_idx].sum(axis=-1)


@jax.jit
def apply(params: Params, x: jax.Array) -> jax.Array:
    """proba_1 per row: (B, F) -> (B,)."""
    return jax.nn.sigmoid(logits(params, x))


def logits_mxu(params: Params, x: jax.Array) -> jax.Array:
    """Gather-free ensemble evaluation: feature selection as ONE matmul.

    The lockstep descent in :func:`logits` does two gathers per level
    (``feat/thr`` by node index, then ``x`` by feature id) — VPU-bound
    dynamic addressing that leaves the MXU idle. TPU-first alternative:

    1. Pre-gather EVERY node's feature value for every row with one
       matmul against a static one-hot matrix:
       ``xv = x @ onehot(feat)`` — (B, F) x (F, T*nI) rides the MXU.
    2. Compare against all thresholds at once -> (B, T, nI) decisions.
    3. Walk the D levels with ``one_hot(idx) * dec`` sums — dense
       elementwise VPU work, no dynamic indexing anywhere.

    FLOP cost grows (every node evaluates, not just the D on the path),
    but the work is MXU-shaped and gather-free — the same trade the
    dense tree embedding itself makes. Exact same semantics as
    :func:`logits` (parity-tested); choose per backend via the
    ``gbt_mxu`` registry entry.

    Measured regimes (BASELINE.md "Model variants"): on CPU the gather
    path wins decisively (221k vs 79k tx/s, BENCH_r02 zoo) — extra FLOPs
    with no systolic array to feed them to. The MXU inversion is the
    HYPOTHESIS this variant exists to test; treat ``gbt_mxu`` as
    experimental until an on-TPU zoo capture records it winning.
    """
    feat, thr, leaf = params["feature"], params["threshold"], params["leaf"]
    n_trees = leaf.shape[0]
    depth = depth_of(params)
    n_int = num_internal(depth)
    # Non-finite features would poison the select-by-matmul (inf * 0 = NaN
    # spreads to EVERY node of the row); map them to huge finite values
    # that preserve the gather path's comparison outcomes: NaN compares
    # False against any finite threshold (like -big), +/-inf compare like
    # +/-big. Dead slots (thr=+inf) stay always-left either way.
    big = jnp.asarray(3.0e38, x.dtype)
    x_safe = jnp.nan_to_num(x, nan=-big, posinf=big, neginf=-big)
    # (F, T*nI) one-hot of each node's split feature. Params are traced
    # jit arguments, so this small build (F x T*nI) runs per call — it is
    # a few percent of the matmul it feeds, not a folded constant.
    onehot = jax.nn.one_hot(
        feat.reshape(-1), x.shape[1], dtype=x.dtype
    ).T  # (F, T*nI)
    xv = (x_safe @ onehot).reshape(x.shape[0], n_trees, n_int)
    dec = (xv > thr[None]).astype(jnp.int32)  # (B, T, nI)
    idx = jnp.zeros((x.shape[0], n_trees), jnp.int32)
    for _ in range(depth):
        # d = dec[b, t, idx[b, t]] without a gather: one-hot mask + sum
        mask = jax.nn.one_hot(idx, n_int, dtype=dec.dtype)
        d = (dec * mask).sum(axis=-1)
        idx = 2 * idx + 1 + d
    leaf_idx = idx - n_int
    leaf_mask = jax.nn.one_hot(leaf_idx, 1 << depth, dtype=leaf.dtype)
    return params["base"] + (leaf[None] * leaf_mask).sum(axis=(-1, -2))


@jax.jit
def apply_mxu(params: Params, x: jax.Array) -> jax.Array:
    """proba_1 per row via the gather-free MXU evaluation."""
    return jax.nn.sigmoid(logits_mxu(params, x))


def apply_numpy(params: Params, x: np.ndarray) -> np.ndarray:
    """Pure-numpy forward, semantically `apply` without a device.

    Enables the serving host latency tier for the tree family (the
    reference's actual model class — sklearn `modelfull`): same lockstep
    descent as `logits`, with numpy gathers. Params must be host arrays.
    """
    from ccfd_tpu.utils.metrics_math import stable_sigmoid

    # callers holding a uniformly-float32 host copy of the params (e.g. a
    # scorer host tier) would otherwise feed float indices into
    # take_along_axis, which raises; already-integer arrays pass through
    # uncopied (this is the per-request host latency path)
    feat = np.asarray(params["feature"])
    if not np.issubdtype(feat.dtype, np.integer):
        feat = feat.astype(np.int64)
    thr = np.asarray(params["threshold"])
    leaf = np.asarray(params["leaf"])
    x = np.asarray(x, np.float32)
    n_trees = leaf.shape[0]
    depth = depth_of(params)
    tree_ids = np.arange(n_trees)[None, :]
    idx = np.zeros((x.shape[0], n_trees), np.int32)
    for _ in range(depth):
        node_feat = feat[tree_ids, idx]  # (B, T)
        node_thr = thr[tree_ids, idx]
        xv = np.take_along_axis(x, node_feat, axis=1)
        idx = 2 * idx + 1 + (xv > node_thr).astype(np.int32)
    leaf_idx = idx - num_internal(depth)
    z = float(params["base"]) + leaf[tree_ids, leaf_idx].sum(axis=-1)
    return stable_sigmoid(z.astype(np.float32))


def _embed_tree(
    children_left: np.ndarray,
    children_right: np.ndarray,
    feature: np.ndarray,
    threshold: np.ndarray,
    value: np.ndarray,
    depth: int,
    scale: float,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n_int = num_internal(depth)
    f = np.zeros(n_int, np.int32)
    t = np.full(n_int, np.inf, np.float32)  # inf => always branch left
    leaves = np.zeros(1 << depth, np.float32)

    def rec(node: int, pos: int, level: int) -> None:
        is_leaf = children_left[node] == -1
        if level == depth:
            if not is_leaf:
                raise ValueError(f"source tree deeper than depth={depth}")
            leaves[pos - n_int] = scale * float(value[node])
            return
        if is_leaf:
            # dead internal slot: keep (feature=0, thr=inf); both subtrees get
            # the leaf's value so the taken path is irrelevant.
            rec(node, 2 * pos + 1, level + 1)
            rec(node, 2 * pos + 2, level + 1)
            return
        f[pos] = int(feature[node])
        t[pos] = float(threshold[node])
        rec(int(children_left[node]), 2 * pos + 1, level + 1)
        rec(int(children_right[node]), 2 * pos + 2, level + 1)

    rec(0, 0, 0)
    return f, t, leaves


def from_sklearn_hgb(clf, max_embed_depth: int = 10) -> Params:
    """Convert a fitted sklearn HistGradientBoostingClassifier (binary) —
    the strongest reference-family model on the canonical table
    (BASELINE.md AUC 0.9650) — into the dense complete-tree embedding.

    Parity: raw_score(x) = baseline + sum_t tree_t(x); leaf values already
    carry shrinkage, and "x <= num_threshold goes left" matches the
    evaluator's ``x > thr`` right branch. The missing-value branch
    (``missing_go_to_left``) is intentionally not embedded: this pipeline
    zero-fills bad cells at decode (native/decode.cpp), so NaN never
    reaches the scorer; categorical splits are rejected.

    HGB grows leaf-count-bounded (default 31 leaves), possibly unbalanced,
    so the complete-binary embedding is exponential in the DEEPEST path:
    ``max_embed_depth`` refuses pathological trees (train with
    ``max_depth<=10`` for servable models) instead of silently allocating
    2^depth nodes per tree.
    """
    if getattr(clf, "n_trees_per_iteration_", 1) != 1:
        raise ValueError("from_sklearn_hgb supports binary classifiers "
                         "only (one tree per boosting iteration)")
    predictors = [p[0] for p in clf._predictors]
    adapters = []
    max_depth_seen = 0
    for pred in predictors:
        nodes = pred.nodes
        if np.any(nodes["is_categorical"]):
            raise ValueError("categorical splits are not embeddable")
        is_leaf = nodes["is_leaf"].astype(bool)
        cl = np.where(is_leaf, -1, nodes["left"].astype(np.int64))
        cr = np.where(is_leaf, -1, nodes["right"].astype(np.int64))
        feat = nodes["feature_idx"].astype(np.int64)
        thr = nodes["num_threshold"].astype(np.float64)
        val = nodes["value"].astype(np.float64)

        def depth_of(node=0, cl=cl, cr=cr):
            if cl[node] == -1:
                return 0
            return 1 + max(depth_of(int(cl[node])), depth_of(int(cr[node])))

        d = depth_of()
        max_depth_seen = max(max_depth_seen, d)
        adapters.append((cl, cr, feat, thr, val))
    if max_depth_seen > max_embed_depth:
        raise ValueError(
            f"HGB tree depth {max_depth_seen} > {max_embed_depth}: the "
            "dense embedding is 2^depth nodes/tree — retrain with "
            "max_depth bounded (e.g. 6-8) for a servable model"
        )
    depth = max(max_depth_seen, 1)
    fs, ts, ls = [], [], []
    for cl, cr, feat, thr, val in adapters:
        f, th, lv = _embed_tree(cl, cr, feat, thr, val, depth, scale=1.0)
        fs.append(f)
        ts.append(th)
        ls.append(lv)
    base = float(np.asarray(clf._baseline_prediction).reshape(()))
    return {
        "feature": jnp.asarray(np.stack(fs)),
        "threshold": jnp.asarray(np.stack(ts)),
        "leaf": jnp.asarray(np.stack(ls)),
        "base": jnp.asarray(base, jnp.float32),
    }


def from_sklearn_gbt(clf) -> Params:
    """Convert a fitted sklearn GradientBoostingClassifier (binary).

    Decision-function parity: score(x) = init_prior + lr * sum_t tree_t(x),
    with sklearn's "x <= threshold goes left" matching our ``x > thr`` right
    branch. The learning rate folds into leaf values; the prior into base.
    """
    trees = [e[0].tree_ for e in clf.estimators_]
    depth = max(t.max_depth for t in trees)
    fs, ts, ls = [], [], []
    for t in trees:
        f, th, lv = _embed_tree(
            t.children_left,
            t.children_right,
            t.feature,
            t.threshold,
            t.value.reshape(-1),
            depth,
            scale=float(clf.learning_rate),
        )
        fs.append(f)
        ts.append(th)
        ls.append(lv)
    # Recover the init prior empirically (robust across sklearn versions):
    # decision_function = base + lr * sum_t tree_t, so probe one row.
    probe = np.zeros((1, clf.n_features_in_), dtype=np.float64)
    tree_sum = float(clf.learning_rate) * sum(float(e[0].predict(probe)[0]) for e in clf.estimators_)
    base = float(np.asarray(clf.decision_function(probe)).reshape(())) - tree_sum
    return {
        "feature": jnp.asarray(np.stack(fs)),
        "threshold": jnp.asarray(np.stack(ts)),
        "leaf": jnp.asarray(np.stack(ls)),
        "base": jnp.asarray(base, jnp.float32),
    }
