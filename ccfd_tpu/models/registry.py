"""Model registry: name -> (init, apply) the way the reference selects its
Seldon graph node by image name (reference deploy/model/modelfull.json:37-44,
``{"name": "modelfull", "type": "MODEL"}``). The serving layer and router look
models up here by the ``CCFD_MODEL`` / ``SELDON_ENDPOINT`` name.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax

from ccfd_tpu.models import logreg, mlp, trees


@dataclass(frozen=True)
class ModelSpec:
    name: str
    init: Callable[..., Any]
    apply: Callable[..., jax.Array]  # (params, x) -> proba_1 (B,)
    logits: Callable[..., jax.Array]
    trainable: bool
    # optional pure-numpy forward: enables the serving host latency tier
    # (small batches skip the device round trip on high-RTT attachments)
    apply_numpy: Callable[..., Any] | None = None


_REGISTRY: dict[str, ModelSpec] = {}


def register_model(spec: ModelSpec) -> None:
    _REGISTRY[spec.name] = spec


def get_model(name: str) -> ModelSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}") from None


register_model(
    ModelSpec("logreg", logreg.init, logreg.apply, logreg.logits,
              trainable=True, apply_numpy=logreg.apply_numpy)
)
register_model(
    ModelSpec("modelfull", logreg.init, logreg.apply, logreg.logits,
              trainable=True, apply_numpy=logreg.apply_numpy)
)  # reference alias: the Seldon graph node name (modelfull.json:38)
register_model(ModelSpec("mlp", mlp.init, mlp.apply, mlp.logits,
                         trainable=True, apply_numpy=mlp.apply_numpy))
register_model(
    ModelSpec(
        "gbt",
        lambda key=None, n_trees=50, depth=4: trees.init_empty(n_trees, depth),
        trees.apply,
        trees.logits,
        trainable=False,
        apply_numpy=trees.apply_numpy,
    )
)

register_model(
    ModelSpec(
        "gbt_mxu",
        lambda key=None, n_trees=50, depth=4: trees.init_empty(n_trees, depth),
        trees.apply_mxu,
        trees.logits_mxu,
        trainable=False,
        apply_numpy=trees.apply_numpy,
    )
)  # gather-free MXU evaluation of the SAME tree params (trees.logits_mxu)

# int8 quantized serving graph: registered here so CCFD_MODEL=mlp_q8 is a
# working drop-in everywhere models resolve by name (quant.py's imports of
# this module are all deferred inside register(), so no cycle)
from ccfd_tpu.ops import quant as _quant  # noqa: E402

_quant.register()

# sequence family: seq (bf16 champion) + seq_q8 (int8 lifecycle-gated
# variant); served through SeqScorer, not the row Scorer — see
# ops/seq_quant.register for the contract
from ccfd_tpu.ops import seq_quant as _seq_quant  # noqa: E402

_seq_quant.register()
