"""The fraud and standard business processes (reference docs/process-fraud.png).

Process semantics follow reference README.md:554-605:

fraud process:
  start -> CustomerNotification (emit to ccd-customer-outgoing)
        -> wait: customer-response signal  vs  no-reply timer
  signal(approved=True)  -> transaction approved   [fraud_approved_amount]
  signal(approved=False) -> transaction cancelled  [fraud_rejected_amount]
  timer -> DMN decision over (amount, fraud probability):
      low amount AND low probability -> auto-approve [fraud_approved_low_amount]
      else -> investigation user task [fraud_investigation_amount]
              (prediction-service may auto-complete, README.md:571-581)
      task outcome is_fraud=True  -> cancelled [fraud_rejected_amount]
      task outcome is_fraud=False -> approved  [fraud_approved_amount]

standard process: approve immediately.

The four amount histograms are the KIE metrics contract
(reference README.md:532-537, deploy/grafana/KIE.json bucket panels).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ccfd_tpu.config import Config
from ccfd_tpu.metrics.prom import AMOUNT_BUCKETS, Registry
from ccfd_tpu.process.clock import Clock
from ccfd_tpu.process.dmn import DecisionTable, Rule
from ccfd_tpu.process.engine import (
    EndNode,
    Engine,
    EventNode,
    GatewayNode,
    Instance,
    ProcessDefinition,
    ServiceNode,
    UserTaskNode,
)

if TYPE_CHECKING:  # pragma: no cover
    from ccfd_tpu.bus.broker import Broker

FRAUD_PROCESS = "fraud"
STANDARD_PROCESS = "standard"
CUSTOMER_RESPONSE_SIGNAL = "customer-response"


def build_engine(
    cfg: Config,
    broker: "Broker",
    registry: Registry | None = None,
    clock: Clock | None = None,
    prediction_service=None,
    task_listener=None,
) -> Engine:
    registry = registry or Registry()
    # CCFD_AUDIT_TOPIC enables the engine's audit stream onto the bus:
    # full lifecycle history survives the runtime store's retention
    # eviction (jBPM's audit-log-vs-runtime separation)
    audit_sink = None
    if cfg.audit_topic:
        # key by pid: one instance's whole history lands on one partition,
        # so consumers replay it in state-change order (cross-instance
        # interleaving is unordered, as in any partitioned audit log).
        # The `batch` attribute lets the engine's batched start path flush
        # a whole micro-batch of events in one produce_batch call.
        def audit_sink(ev):
            broker.produce(cfg.audit_topic, ev, key=ev["pid"])

        audit_sink.batch = lambda evs: broker.produce_batch(
            cfg.audit_topic, evs, keys=[e["pid"] for e in evs]
        )
    engine = Engine(
        clock=clock,
        registry=registry,
        prediction_service=prediction_service,
        confidence_threshold=cfg.confidence_threshold,
        task_listener=task_listener,
        audit_sink=audit_sink,
    )

    h_invest = registry.histogram(
        "fraud_investigation_amount", "amounts sent to investigation", AMOUNT_BUCKETS
    )
    h_low = registry.histogram(
        "fraud_approved_low_amount", "amounts auto-approved by DMN", AMOUNT_BUCKETS
    )
    h_approved = registry.histogram(
        "fraud_approved_amount", "amounts approved", AMOUNT_BUCKETS
    )
    h_rejected = registry.histogram(
        "fraud_rejected_amount", "amounts rejected/cancelled", AMOUNT_BUCKETS
    )

    # DMN: accept vs investigate by amount + model probability (README.md:583-605)
    triage = DecisionTable(
        name="fraud-triage",
        rules=[
            Rule(
                when={
                    "amount": ("<", cfg.low_amount_threshold),
                    "proba": ("<", cfg.low_proba_threshold),
                },
                then="auto_approve_low",
            )
        ],
        default="open_investigation",
    )

    def amount_of(inst: Instance) -> float:
        return float(inst.vars.get("transaction", {}).get("Amount", 0.0))

    def notify(engine_: Engine, inst: Instance) -> None:
        # trace carriage (observability/trace.py): process starts run on
        # the router's thread inside its route span, so the notification
        # record inherits the batch's trace context and the notify
        # service's reply leg stays on the SAME end-to-end trace. Timer-
        # driven notifications (engine clock thread) have no active span
        # and ride unstamped.
        from ccfd_tpu.observability.trace import inject_headers

        headers = inject_headers()
        broker.produce(
            cfg.customer_notification_topic,
            {
                "process_id": inst.pid,
                "customer_id": inst.vars.get("customer_id", inst.vars.get("transaction", {}).get("id")),
                "transaction": inst.vars.get("transaction", {}),
            },
            key=inst.pid,
            **({"headers": headers} if headers else {}),
        )

    def on_reply(engine_: Engine, inst: Instance) -> str:
        payload = inst.vars.get("signal_payload") or {}
        return "approve" if payload.get("approved") else "cancel"

    def dmn_choose(engine_: Engine, inst: Instance) -> str:
        out = triage.evaluate(
            {"amount": amount_of(inst), "proba": float(inst.vars.get("proba", 1.0))}
        )
        return out

    def task_outcome(engine_: Engine, inst: Instance) -> str:
        return "cancel" if inst.vars.get("task_outcome") else "approve"

    def record(hist, label: int | None = None):
        """Observe the KIE amount histogram and, when the resolution carries a
        ground-truth fraud label, publish it for online retraining
        (BASELINE.json configs[4]: SGD from jBPM human-task labels)."""

        def fn(engine_: Engine, inst: Instance) -> None:
            hist.observe(amount_of(inst))
            inst.vars["resolution"] = hist.name
            if label is not None:
                broker.produce(
                    cfg.labels_topic,
                    {
                        "transaction": inst.vars.get("transaction", {}),
                        "label": label,
                        "process_id": inst.pid,
                        "source": hist.name,
                    },
                    key=inst.pid,
                )

        return fn

    fraud = ProcessDefinition(
        id=FRAUD_PROCESS,
        start="notify",
        nodes={
            "notify": ServiceNode("notify", notify, next="await_reply"),
            "await_reply": EventNode(
                "await_reply",
                signal=CUSTOMER_RESPONSE_SIGNAL,
                timeout_s=cfg.customer_reply_timeout_s,
                on_signal="reply_gateway",
                on_timeout="dmn",
            ),
            "reply_gateway": GatewayNode("reply_gateway", on_reply),
            "dmn": GatewayNode("dmn", dmn_choose),
            "auto_approve_low": ServiceNode(
                "auto_approve_low", record(h_low), next="end_approved"
            ),
            "open_investigation": ServiceNode(
                "open_investigation", record(h_invest), next="investigate"
            ),
            "investigate": UserTaskNode(
                "investigate", task_name="fraud-investigation", next="outcome_gateway"
            ),
            "outcome_gateway": GatewayNode("outcome_gateway", task_outcome),
            "approve": ServiceNode(
                "approve", record(h_approved, label=0), next="end_approved"
            ),
            "cancel": ServiceNode(
                "cancel", record(h_rejected, label=1), next="end_cancelled"
            ),
            "end_approved": EndNode("end_approved", status="completed"),
            "end_cancelled": EndNode("end_cancelled", status="cancelled"),
        },
    )

    standard = ProcessDefinition(
        id=STANDARD_PROCESS,
        start="approve",
        nodes={
            "approve": ServiceNode(
                "approve", lambda e, i: i.vars.__setitem__("resolution", "approved"),
                next="end",
            ),
            "end": EndNode("end"),
        },
    )

    engine.register(fraud)
    engine.register(standard)
    return engine
