"""Investigator simulation: the humans working the KIE console queue.

The reference demo's loop closes through people — investigators open the
Business Central task list, see the prediction service's pre-filled
recommendation, and approve or cancel the transaction (reference
README.md:547-581). Without that actor, flagged transactions park as open
tasks forever, and the online user-task model (process/usertask_model.py)
— which trains on INVESTIGATOR decisions — never sees a label.

This service is that actor, seeded and rate-limited like the customer
simulation in notify/service.py:

- polls the engine's open-task queue (in-process ``Engine`` or the
  KIE-shaped REST client — both task surfaces are accepted),
- when the console pre-fill is confident enough
  (``prediction_confidence >= trust_threshold``), follows the suggestion
  (the measured behavior auto-close is modeled on: humans rubber-stamp
  high-confidence recommendations),
- otherwise decides independently: fraud with probability
  ``base_fraud_rate`` (seeded), the shape of a queue whose flags are
  mostly false positives,
- at most ``rate_per_s`` completions per second — a queue fed faster
  than the investigators drain it grows, visible on the KIE board's
  open-task stats, exactly like the real console backlog.

Metrics: ``investigator_tasks_completed_total`` (by outcome) and
``investigator_queue_depth``. Run under the supervisor (operator
component ``investigator``) or standalone via ``ccfd_tpu investigate``.
"""
from __future__ import annotations

import threading
import time
from typing import Any

import numpy as np

from ccfd_tpu.metrics.prom import Registry


def _field(task: Any, name: str, default: Any = None) -> Any:
    """Task field access across both surfaces: Engine yields Task objects,
    the REST client yields plain dicts."""
    if isinstance(task, dict):
        return task.get(name, default)
    return getattr(task, name, default)


class InvestigatorService:
    def __init__(
        self,
        engine: Any,
        registry: Registry | None = None,
        rate_per_s: float = 50.0,
        trust_threshold: float = 0.9,
        base_fraud_rate: float = 0.05,
        seed: int = 0,
        batch: int = 100,
    ):
        self.engine = engine
        self.registry = registry or Registry()
        self.rate_per_s = float(rate_per_s)
        self.trust_threshold = float(trust_threshold)
        self.base_fraud_rate = float(base_fraud_rate)
        self.batch = int(batch)
        self._rng = np.random.default_rng(seed)
        self._stop = threading.Event()
        self._c_done = self.registry.counter(
            "investigator_tasks_completed_total",
            "investigator task completions by outcome",
        )
        self._g_queue = self.registry.gauge(
            "investigator_queue_depth", "open tasks awaiting investigation"
        )
        self.completed = 0

    # -- one decision ------------------------------------------------------
    def decide(self, task: Any) -> bool:
        """The verdict (is_fraud) for one task."""
        conf = _field(task, "prediction_confidence") or 0.0
        suggested = _field(task, "suggested_outcome")
        if suggested is not None and conf >= self.trust_threshold:
            return bool(suggested)
        return bool(self._rng.random() < self.base_fraud_rate)

    def work_once(self) -> int:
        """One pass over the queue (bounded by ``batch``); returns the
        number of tasks completed. Engine swaps (crash recovery) and
        already-completed tasks surface as exceptions on individual
        completions — those are skipped, the rest of the pass continues."""
        try:
            tasks = self.engine.tasks("open")
        except Exception:  # noqa: BLE001 - engine mid-restart: next pass
            return 0
        self._g_queue.set(float(len(tasks)))
        done = 0
        for task in tasks[: self.batch]:
            if self._stop.is_set():
                break
            verdict = self.decide(task)
            try:
                self.engine.complete_task(_field(task, "task_id"), verdict)
            except Exception:  # noqa: BLE001 - task gone / engine swapped
                continue
            self._c_done.inc(labels={
                "outcome": "cancelled" if verdict else "approved"
            })
            self.completed += 1
            done += 1
            if self.rate_per_s > 0:
                # interruptible pacing: a slow configured rate must not
                # stall stop()/platform.down() for up to 1/rate seconds
                if self._stop.wait(1.0 / self.rate_per_s):
                    break
        return done

    # -- service lifecycle -------------------------------------------------
    def run(self, poll_timeout_s: float = 0.2) -> None:
        while not self._stop.is_set():
            if self.work_once() == 0:
                self._stop.wait(poll_timeout_s)

    def stop(self) -> None:
        self._stop.set()

    def reset(self) -> None:
        """Supervisor respawn hook (must not run on the service thread)."""
        self._stop.clear()
