"""Learned user-task outcome model — the reference's second Seldon model.

The reference deploys a dedicated Seldon model
(``ruivieira/ccfd-seldon-usertask-model``, reference README.md:347-353)
whose sole job is predicting the outcome of jBPM investigation user tasks:
confidence >= ``CONFIDENCE_THRESHOLD`` auto-closes the task with the
predicted outcome, lower confidence only pre-fills it (README.md:571-581,
docs/images/events-3.final.png). That model is trained on investigators'
past decisions.

TPU-native re-design: ``OnlineUserTaskModel`` is both the prediction
service and its trainer in one object —

- ``predict(task)`` scores a (1, 31) row — the 30 transaction features
  plus the fraud probability the router attached — through a jitted
  logistic regression. Confidence is the margin ``max(p, 1-p)``.
- ``observe(task)`` ingests a HUMAN task completion as a labeled example.
  Auto-completed tasks are never observed: learning from the model's own
  auto-closures would be feedback, not supervision — jBPM likewise trains
  its prediction service on investigator decisions only.
- Every ``fit_every`` observations it runs a few jitted SGD epochs over
  the example buffer and atomically swaps the params it serves.

Until ``min_examples`` human decisions exist, ``predict`` returns zero
confidence, so every task stays open for a human — the cold-start behavior
the reference gets by shipping the user-task model separately.

The engine hook is ``Engine(task_listener=...)``: called once per human
``complete_task`` with the finished task.
"""

from __future__ import annotations

import atexit
import os
import threading
import weakref
from typing import TYPE_CHECKING, Any

import jax
import jax.numpy as jnp
import numpy as np

from ccfd_tpu.data.ccfd import FEATURE_NAMES

if TYPE_CHECKING:  # pragma: no cover
    from ccfd_tpu.process.engine import Task

NUM_TASK_FEATURES = len(FEATURE_NAMES) + 1  # + fraud probability

# Models whose construction-time warmup thread may still be compiling; a
# WeakSet so discarded models are collectable. The single atexit hook stops
# and joins the stragglers (a thread mid-XLA-compile killed at exit aborts
# the process with "exception not rethrown").
_live_warmups: "weakref.WeakSet[OnlineUserTaskModel]" = weakref.WeakSet()
_atexit_registered = False


def _register_warmup(model: "OnlineUserTaskModel") -> None:
    global _atexit_registered
    _live_warmups.add(model)
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_cancel_all_warmups)


def _cancel_all_warmups() -> None:
    for m in list(_live_warmups):
        m._warmup_cancel()


def task_row(task: "Task") -> np.ndarray:
    """(1, 31) float32: transaction features + attached fraud probability.

    Delegates the 30 transaction columns to ``prediction.task_features`` so
    both prediction services extract features identically (including the
    flat-vars fallback when no "transaction" dict is present).
    """
    from ccfd_tpu.process.prediction import task_features

    feats = task_features(task)
    proba = np.asarray([[float(task.vars.get("proba", 0.0))]], np.float32)
    return np.concatenate([feats, proba], axis=1)


@jax.jit
def _predict(params, x):
    xs = (x - params["mean"]) / params["scale"]
    z = jnp.dot(xs, params["w"], preferred_element_type=jnp.float32) + params["b"]
    return jax.nn.sigmoid(z)


@jax.jit
def _sgd_epoch(params, x, y, m, lr):
    """One full-batch logistic-regression step over pre-standardized rows
    (the buffer IS the batch: investigator decisions are rare, so
    full-batch beats minibatching). ``m`` masks padding rows — the batch is
    padded to a power-of-two bucket so XLA compiles one executable instead
    of one per buffer length.
    """

    def loss_fn(p):
        z = jnp.dot(x, p["w"], preferred_element_type=jnp.float32) + p["b"]
        # weighted BCE over real rows only: outcomes can be imbalanced
        n = jnp.maximum(jnp.sum(m), 1.0)
        n_pos = jnp.maximum(jnp.sum(y * m), 1.0)
        n_neg = jnp.maximum(jnp.sum((1.0 - y) * m), 1.0)
        w_pos = n / (2.0 * n_pos)
        w_neg = n / (2.0 * n_neg)
        ll = jax.nn.log_sigmoid(z) * y * w_pos + jax.nn.log_sigmoid(-z) * (1.0 - y) * w_neg
        return -jnp.sum(ll * m) / n

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new = {k: params[k] - lr * grads[k] for k in ("w", "b")}
    return {**params, **new}, loss


class OnlineUserTaskModel:
    """Prediction service + online trainer for investigation outcomes."""

    def __init__(
        self,
        min_examples: int = 32,
        fit_every: int = 8,
        epochs: int = 50,
        learning_rate: float = 0.5,
        buffer_size: int = 4096,
        seed: int = 0,
        warmup: bool = True,
    ):
        self.min_examples = min_examples
        self.fit_every = fit_every
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.buffer_size = buffer_size
        key = jax.random.PRNGKey(seed)
        self._params = {
            "w": jax.random.normal(key, (NUM_TASK_FEATURES,), jnp.float32) * 0.01,
            "b": jnp.zeros((), jnp.float32),
            # feature standardization learned from the buffer at fit time
            # (raw Amounts span orders of magnitude; GD on raw scales
            # diverges) — carried with the params so predict() matches
            "mean": jnp.zeros((NUM_TASK_FEATURES,), jnp.float32),
            "scale": jnp.ones((NUM_TASK_FEATURES,), jnp.float32),
        }
        self._x: list[np.ndarray] = []
        self._y: list[float] = []
        self._seen = 0
        self._trained = False
        self._lock = threading.Lock()
        self.last_loss: float | None = None
        # Pre-compile the jitted predict/fit executables off the request
        # path: the first _fit would otherwise run XLA compilation
        # synchronously inside the investigator's complete_task call (the
        # engine task_listener fires in the REST handler thread), and every
        # new power-of-two buffer bucket would recompile again. Warming on a
        # daemon thread at construction covers every bucket this buffer can
        # ever reach, so human task completions never pay a compile.
        self._warmup_thread: threading.Thread | None = None
        self._warmup_stop = threading.Event()
        if warmup:
            self._warmup_thread = threading.Thread(
                target=self._warmup, name="usertask-model-warmup", daemon=True
            )
            self._warmup_thread.start()
            # a daemon thread killed mid-XLA-compile at interpreter exit
            # aborts the process ("exception not rethrown"); stop between
            # buckets and join instead. One module-level atexit hook over a
            # WeakSet — registering a bound method per instance would pin
            # every model (params + example buffer) until interpreter exit.
            _register_warmup(self)

    def _warmup(self) -> None:
        try:
            params = self._params
            _predict(params, jnp.zeros((1, NUM_TASK_FEATURES), jnp.float32))
            lr = jnp.float32(self.learning_rate)
            bucket = 1
            while bucket < self.min_examples:
                bucket *= 2
            while not self._warmup_stop.is_set():
                x = jnp.zeros((bucket, NUM_TASK_FEATURES), jnp.float32)
                y = jnp.zeros((bucket,), jnp.float32)
                _sgd_epoch(params, x, y, y, lr)
                if bucket >= self.buffer_size:  # pow2 ceiling covered
                    break
                bucket *= 2
        except Exception:  # pragma: no cover - warmup is best-effort
            pass

    def _warmup_cancel(self) -> None:
        self._warmup_stop.set()
        if self._warmup_thread is not None:
            # bounded join: if a compile wedged (e.g. a hung device tunnel)
            # the thread never sees the stop event — cap the wait so
            # interpreter exit is never blocked forever
            self._warmup_thread.join(timeout=10.0)

    def warmup_join(self, timeout: float | None = None) -> None:
        """Block until the construction-time compile warmup finishes
        (benchmarks and tests that measure fit latency call this first)."""
        if self._warmup_thread is not None:
            self._warmup_thread.join(timeout)

    # -- PredictionService protocol ---------------------------------------
    def predict(self, task: "Task") -> tuple[Any, float]:
        with self._lock:
            trained = self._trained
            params = self._params
        if not trained:
            # cold start: no investigator signal yet -> never auto-close,
            # nothing to pre-fill
            return None, 0.0
        p = float(_predict(params, jnp.asarray(task_row(task)))[0])
        outcome = p >= 0.5
        return outcome, max(p, 1.0 - p)

    # -- engine task_listener ---------------------------------------------
    def observe(self, task: "Task") -> None:
        """Ingest a human-completed task; refit when enough new ones landed."""
        if task.status != "completed":
            return
        with self._lock:
            self._x.append(task_row(task)[0])
            self._y.append(1.0 if task.outcome else 0.0)
            if len(self._x) > self.buffer_size:
                self._x = self._x[-self.buffer_size:]
                self._y = self._y[-self.buffer_size:]
            self._seen += 1
            n = len(self._x)
            due = n >= self.min_examples and (
                not self._trained or self._seen % self.fit_every == 0
            )
            if not due:
                return
            x = np.stack(self._x)
            y = np.asarray(self._y, np.float32)
            params = self._params
        self._fit(params, x, y)

    def _fit(self, params, x: np.ndarray, y: np.ndarray) -> None:
        # train outside the lock: predict() keeps serving the old params
        mu = x.mean(axis=0)
        sigma = x.std(axis=0)
        sigma = np.where(sigma < 1e-6, 1.0, sigma)
        params = {
            **params,
            "mean": jnp.asarray(mu, jnp.float32),
            "scale": jnp.asarray(sigma, jnp.float32),
        }
        # pad to a power-of-two bucket: one compiled executable instead of a
        # recompile per buffer length (each fit would otherwise stall a
        # human complete_task call on a fresh XLA compile)
        n = x.shape[0]
        bucket = 1
        while bucket < n:
            bucket *= 2
        xs = np.zeros((bucket, x.shape[1]), np.float32)
        xs[:n] = (x - mu) / sigma
        ys = np.zeros((bucket,), np.float32)
        ys[:n] = y
        mask = np.zeros((bucket,), np.float32)
        mask[:n] = 1.0
        x_j, y_j, m_j = jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(mask)
        lr = jnp.float32(self.learning_rate)
        loss = None
        for _ in range(self.epochs):
            params, loss = _sgd_epoch(params, x_j, y_j, m_j, lr)
        jax.block_until_ready(loss)
        with self._lock:
            self._params = params
            self._trained = True
            self.last_loss = float(loss)

    @property
    def n_examples(self) -> int:
        with self._lock:
            return len(self._x)

    @property
    def trained(self) -> bool:
        with self._lock:
            return self._trained

    # -- persistence (restarts must not discard investigator supervision) --
    def save(self, path: str) -> None:
        """Checksummed atomic .npz of params + example buffer (tmp +
        fsync + rename with generation retention, runtime/durability.py)."""
        import io

        from ccfd_tpu.runtime.durability import write_artifact

        with self._lock:
            params = {k: np.asarray(v) for k, v in self._params.items()}
            x = np.stack(self._x) if self._x else np.zeros((0, NUM_TASK_FEATURES), np.float32)
            y = np.asarray(self._y, np.float32)
            trained = self._trained
            seen = self._seen
        buf = io.BytesIO()  # file object: savez won't append .npz
        np.savez(buf, x=x, y=y, trained=trained, seen=seen, **params)
        write_artifact(path, buf.getvalue(), artifact="usertask")

    def load(self, path: str) -> None:
        """Verified restore: a corrupt file quarantines and falls back to
        the last-good retained generation."""
        import io

        from ccfd_tpu.runtime.durability import read_artifact

        data = np.load(io.BytesIO(read_artifact(path, artifact="usertask")))
        with self._lock:
            self._params = {
                k: jnp.asarray(data[k]) for k in ("w", "b", "mean", "scale")
            }
            self._x = list(data["x"])
            self._y = [float(v) for v in data["y"]]
            self._trained = bool(data["trained"])
            self._seen = int(data["seen"])
