"""KIE-server-shaped REST surface for the process engine.

The reference's jBPM engine is driven over REST on port 8090: the router
starts processes and forwards customer-response signals via
``KIE_SERVER_URL`` (reference deploy/router.yaml:63-64, README.md:552,569),
and Prometheus scrapes ``:8090/rest/metrics`` (README.md:509-515). This
module gives the in-tree engine the same network surface so the router,
investigator tooling, and scrapers can live in different processes than
the engine:

    POST /rest/processes/{def_id}/instances   {variables}      -> {process_id}
    POST /rest/instances/{pid}/signal/{name}  {payload}        -> {consumed}
    GET  /rest/instances/{pid}                                 -> instance view
    GET  /rest/instances?status=active                         -> [instance view]
    GET  /rest/tasks?status=open                               -> [task view]
    POST /rest/tasks/{tid}/complete           {outcome}        -> {}
    GET  /rest/metrics | /metrics              Prometheus scrape (KIE path)
    GET  /health/status                        readiness

Same threaded stdlib HTTP server approach as the scoring server
(ccfd_tpu/serving/server.py): a fixed contract needs no framework, and the
engine does its own locking so handlers stay thin.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler
from typing import Any

from ccfd_tpu.utils.httpserver import FrameworkHTTPServer

from ccfd_tpu.process.engine import Engine, Instance, Task

_INSTANCES = re.compile(r"^/rest/processes/([\w.-]+)/instances$")
_INSTANCES_BATCH = re.compile(r"^/rest/processes/([\w.-]+)/instances/batch$")
_SIGNAL = re.compile(r"^/rest/instances/(\d+)/signal/([\w.-]+)$")
_INSTANCE = re.compile(r"^/rest/instances/(\d+)$")
_COMPLETE = re.compile(r"^/rest/tasks/(\d+)/complete$")


def instance_view(i: Instance) -> dict[str, Any]:
    return {
        "process_id": i.pid,
        "definition": i.definition.id,
        "status": i.status,
        "node": i.node,
        # copy under the caller-held lock: json.dumps runs after release,
        # and the engine mutates vars keys in place (signal_payload etc.)
        "vars": dict(i.vars),
    }


def task_view(t: Task) -> dict[str, Any]:
    return {
        "task_id": t.task_id,
        "process_id": t.pid,
        "name": t.name,
        "status": t.status,
        "suggested_outcome": t.suggested_outcome,
        "prediction_confidence": t.prediction_confidence,
        "outcome": t.outcome,
        "vars": dict(t.vars),
    }


class EngineServer:
    def __init__(self, engine: Engine, tracer=None):
        self.engine = engine
        # observability/trace.py: mutating requests (process starts,
        # signals, task completions) join the caller's trace via the
        # traceparent header -> "engine.rest" server span
        self.tracer = tracer
        self._httpd: FrameworkHTTPServer | None = None

    def _handler_class(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _send_json(self, code: int, obj: Any) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code: int, text: str) -> None:
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/") or "/"
                eng = server.engine
                if path in ("/rest/metrics", "/metrics", "/prometheus"):
                    self._send_text(200, eng.registry.render())
                    return
                if path in ("/health/status", "/health", "/healthz"):
                    self._send_json(
                        200, {"status": "ok", "definitions": list(eng.definitions())}
                    )
                    return
                # views serialize live vars dicts: hold the engine lock so a
                # concurrent signal can't mutate them mid-iteration
                m = _INSTANCE.match(path)
                if m:
                    with eng.state_lock:
                        try:
                            view = instance_view(eng.instance(int(m.group(1))))
                        except KeyError:
                            view = None
                    if view is None:
                        self._send_json(404, {"error": "no such instance"})
                    else:
                        self._send_json(200, view)
                    return
                if path == "/rest/instances":
                    status = _param(query, "status")
                    with eng.state_lock:
                        views = [instance_view(i) for i in eng.instances(status)]
                    self._send_json(200, views)
                    return
                if path == "/rest/tasks":
                    status = _param(query, "status") or "open"
                    with eng.state_lock:
                        views = [task_view(t) for t in eng.tasks(status)]
                    self._send_json(200, views)
                    return
                self._send_json(404, {"error": "not found"})

            def do_POST(self):
                if server.tracer is None:
                    self._handle_post()
                    return
                from ccfd_tpu.observability.trace import extract_context

                with server.tracer.span(
                    "engine.rest",
                    parent=extract_context(self.headers),
                    attrs={"path": self.path.split("?")[0]},
                ):
                    self._handle_post()

            def _handle_post(self):
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    length = 0
                raw = self.rfile.read(length) if length else b"{}"
                try:
                    payload = json.loads(raw or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._send_json(400, {"error": "malformed JSON body"})
                    return
                if not isinstance(payload, dict):
                    self._send_json(400, {"error": "JSON body must be an object"})
                    return
                path = self.path.rstrip("/")
                eng = server.engine
                m = _INSTANCES_BATCH.match(path)
                if m:
                    vlist = payload.get("variables_list")
                    if not isinstance(vlist, list):
                        self._send_json(
                            400, {"error": "variables_list must be a list"}
                        )
                        return
                    try:
                        pids = eng.start_process_batch(m.group(1), vlist)
                    except KeyError:
                        self._send_json(404, {"error": f"no process {m.group(1)!r}"})
                        return
                    self._send_json(201, {"process_ids": pids})
                    return
                m = _INSTANCES.match(path)
                if m:
                    try:
                        pid = eng.start_process(
                            m.group(1), payload.get("variables", payload) or {}
                        )
                    except KeyError:
                        self._send_json(404, {"error": f"no process {m.group(1)!r}"})
                        return
                    self._send_json(201, {"process_id": pid})
                    return
                m = _SIGNAL.match(path)
                if m:
                    consumed = eng.signal(
                        int(m.group(1)), m.group(2), payload.get("payload", payload)
                    )
                    self._send_json(200, {"consumed": consumed})
                    return
                m = _COMPLETE.match(path)
                if m:
                    try:
                        eng.complete_task(int(m.group(1)), payload.get("outcome"))
                    except KeyError:
                        self._send_json(404, {"error": "no such task"})
                        return
                    except ValueError as e:
                        self._send_json(409, {"error": str(e)})
                        return
                    self._send_json(200, {})
                    return
                self._send_json(404, {"error": "not found"})

        return Handler

    def start(self, host: str = "0.0.0.0", port: int = 8090) -> int:
        self._httpd = FrameworkHTTPServer((host, port), self._handler_class())
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="ccfd-kie"
        ).start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _param(query: str, name: str) -> str | None:
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k == name and v:
            return v
    return None
