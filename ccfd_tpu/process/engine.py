"""Business-process engine: the jBPM/KIE-server capability, TPU-framework native.

The reference runs fraud/standard processes on a KIE execution server
(reference deploy/ccd-service.yaml:1-124; semantics README.md:583-605 and
docs/process-fraud.png): a customer-notification node, a no-reply timer
racing a customer-response signal, a DMN decision over amount+probability,
a user task for human investigators, and a Seldon-backed prediction service
that auto-completes user tasks at high confidence
(``-Dorg.jbpm.task.prediction.service=SeldonPredictionService``,
ccd-service.yaml:65-66; confidence semantics README.md:571-581).

This engine re-creates those semantics as an explicit state machine:

- A ``ProcessDefinition`` is a named graph of nodes; node kinds are
  ``ServiceNode`` (run a function, move on), ``EventNode`` (wait for a
  signal OR a timer — whichever fires first wins, atomically),
  ``UserTaskNode`` (open a human task, consult the prediction service),
  and ``EndNode``.
- The signal-vs-timer race is resolved under one engine lock with a
  per-wait generation counter: the first of {matching signal, timer with
  matching generation} consumes the wait; the loser is a no-op.
- The prediction service hook mirrors jBPM's: confidence >=
  ``confidence_threshold`` auto-completes the task with the predicted
  outcome; below it, the prediction is only pre-filled as
  ``task.suggested_outcome`` (README.md:580-581).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Protocol, Sequence

from ccfd_tpu.metrics.prom import Registry
from ccfd_tpu.process.clock import Clock, RealClock, TimerHandle

# process-wide engine-object sequence for audit-event provenance
_ENGINE_SEQ = itertools.count(1)

def _copy_containers(v: Any) -> Any:
    """Recursive copy of JSON containers (dict/list), leaves shared.

    Snapshots detach from live engine state with this instead of a full
    ``json.dumps`` under the lock: copying containers is cheap (no string
    building), and since dicts/lists are the only mutable JSON values, a
    ServiceNode that mutates NESTED vars (``inst.vars["x"]["y"] = ...``)
    still can't tear the snapshot serialized after the lock is released.
    """
    if isinstance(v, dict):
        return {k: _copy_containers(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_copy_containers(x) for x in v]
    return v


# ---------------------------------------------------------------------------
# Nodes


@dataclass(frozen=True)
class ServiceNode:
    name: str
    fn: Callable[["Engine", "Instance"], None]
    next: str


@dataclass(frozen=True)
class EventNode:
    """Wait for ``signal`` or a timer of ``timeout_s`` — first one wins."""

    name: str
    signal: str
    timeout_s: float | Callable[["Instance"], float]
    on_signal: str
    on_timeout: str


@dataclass(frozen=True)
class UserTaskNode:
    name: str
    task_name: str
    next: str  # node run after completion; outcome in vars["task_outcome"]


@dataclass(frozen=True)
class GatewayNode:
    """Exclusive (XOR) gateway: choose() names the next node."""

    name: str
    choose: Callable[["Engine", "Instance"], str]


@dataclass(frozen=True)
class EndNode:
    name: str
    status: str = "completed"


Node = ServiceNode | EventNode | GatewayNode | UserTaskNode | EndNode


@dataclass(frozen=True)
class ProcessDefinition:
    id: str
    start: str
    nodes: Mapping[str, Node]

    def __post_init__(self) -> None:
        for n in self.nodes.values():
            targets = [
                t
                for t in (
                    getattr(n, "next", None),
                    getattr(n, "on_signal", None),
                    getattr(n, "on_timeout", None),
                )
                if t is not None
            ]
            for t in targets:
                if t not in self.nodes:
                    raise ValueError(f"{self.id}:{n.name} -> unknown node {t!r}")
        if self.start not in self.nodes:
            raise ValueError(f"{self.id}: unknown start node {self.start!r}")


# ---------------------------------------------------------------------------
# Runtime state


@dataclass(slots=True)
class Instance:
    pid: int
    definition: ProcessDefinition
    vars: dict[str, Any]
    status: str = "active"  # active | completed | aborted
    node: str = ""
    wait_signal: str | None = None
    wait_gen: int = 0
    timer: TimerHandle | None = None
    timer_deadline: float | None = None  # clock.now()-relative; for snapshots
    history: list[str] = field(default_factory=list)


@dataclass(slots=True)
class Task:
    task_id: int
    pid: int
    name: str
    vars: dict[str, Any]
    status: str = "open"  # open | completed
    suggested_outcome: Any = None
    prediction_confidence: float | None = None
    outcome: Any = None


class PredictionService(Protocol):
    """jBPM prediction-service shape: predict a user-task outcome."""

    def predict(self, task: Task) -> tuple[Any, float]: ...


# ---------------------------------------------------------------------------
# Engine


class Engine:
    def __init__(
        self,
        clock: Clock | None = None,
        registry: Registry | None = None,
        prediction_service: PredictionService | None = None,
        confidence_threshold: float = 1.0,
        task_listener: Callable[[Task], None] | None = None,
        completed_retention: int = 10_000,
        audit_sink: Callable[[dict[str, Any]], None] | None = None,
        audit_evict: bool = True,
        postmortem_retention: int = 2048,
    ):
        self.clock: Clock = clock or RealClock()
        self.registry = registry or Registry()
        self.prediction_service = prediction_service
        self.confidence_threshold = confidence_threshold
        # fired once per HUMAN complete_task (never for prediction-service
        # auto-completions): the user-task model trains on investigator
        # decisions only — learning from its own auto-closures would be
        # feedback, not supervision
        self.task_listener = task_listener
        # Audit stream (jBPM's AuditService analog): lifecycle events —
        # process_started/process_completed, task_created/task_completed,
        # signal, timer_fired — reach this sink in state-change order.
        # Events BUFFER under the state lock and deliver after it releases
        # (public entry points flush), so a slow sink (a remote bus hop)
        # never stalls the engine's lock; the flush lock serializes
        # deliveries so per-pid order still matches state-change order.
        # A sink exposing a ``batch`` attribute gets each flush in ONE
        # call. None (default) costs nothing on the hot path. The runtime
        # store evicts completed instances (retention cap below); the
        # audit stream is where full history durably lives.
        self._audit = audit_sink
        self._audit_buffer: list[dict[str, Any]] = []
        self._audit_flush_lock = threading.Lock()
        self._definitions: dict[str, ProcessDefinition] = {}
        self._instances: dict[int, Instance] = {}
        self._tasks: dict[int, Task] = {}
        self._pid = itertools.count(1)
        self._tid = itertools.count(1)
        self._lock = threading.RLock()
        # Completed instances are evicted FIFO past this cap (jBPM likewise
        # drops finished instances from the runtime store, keeping history in
        # the audit log — here, in metrics): a pipeline starting a process
        # per scored transaction would otherwise grow ``_instances`` without
        # bound at tens of thousands of entries per second.
        self._completed_retention = completed_retention
        self._completed_order: deque[int] = deque()
        # Audit-coupled eviction (the round-8 RSS-drift fix): with an audit
        # sink wired, a completed instance's full state leaves the runtime
        # store as soon as its ``process_completed`` event has actually
        # been DELIVERED to the sink (for the bus sink that means the
        # durable log already holds it — bus/broker.py writes the log
        # before the in-memory append). The 10k ``completed_retention``
        # FIFO then only backstops sink failures. Without a sink the
        # historical cap is the only eviction, as before.
        self._audit_evict = bool(audit_evict)
        # bounded post-mortem ring: evicted instances stay queryable as
        # lightweight summaries (pid/definition/status/ts) — what the soak's
        # tail-completion reconciliation and operators' "what happened to
        # pid X" need, at ~100 B instead of a full Instance + tasks
        self._postmortem_retention = int(postmortem_retention)
        # pid -> (definition_id, status, ts) — tuples, not dicts (hot
        # path); completed_info/recent_completions rebuild dicts on query
        self._postmortem: dict[int, tuple[str, str, float]] = {}
        self._tasks_by_pid: dict[int, list[int]] = {}
        # def_id -> (service_nodes, end_node, history) for straight-through
        # definitions (ServiceNode chain into an EndNode, no waits/gateways/
        # tasks): the hot batch path runs these without per-node dispatch
        self._static_chains: dict[str, tuple[list[ServiceNode], EndNode, list[str]]] = {}
        # set by shutdown(): a decommissioned engine object must go silent
        self._dead = False
        # stamped into every audit event: across crash-recovery swaps
        # (runtime/recovery.py) multiple engine objects write one stream,
        # and epoch forensics need to know which object emitted what
        self._engine_tag = f"e{next(_ENGINE_SEQ)}"
        self._started = self.registry.counter(
            "process_instances_started_total", "process starts by definition"
        )
        self._completed = self.registry.counter(
            "process_instances_completed_total", "process completions by status"
        )

    def _emit(self, event: str, pid: int, process: str, **extra: Any) -> None:
        """Buffer one audit event; caller holds the state lock and has
        checked ``self._audit is not None`` (so the off case builds no
        dicts). Delivery happens in ``_flush_audit`` after lock release."""
        self._audit_buffer.append({
            "event": event, "pid": pid, "process": process,
            "ts": self.clock.now(), "engine": self._engine_tag, **extra,
        })

    def _flush_audit(self) -> None:
        """Deliver buffered audit events OUTSIDE the state lock.

        The flush lock serializes concurrent flushers, and the buffer swap
        happens under the state lock inside it — so delivery order equals
        state-change order even when two API calls race to flush. A sink
        exposing a ``batch`` attribute gets the whole flush in one call
        (the bus sink maps it to produce_batch); otherwise events deliver
        one at a time with per-event failure isolation."""
        if self._audit is None:
            return
        # Reentrancy guard: a ServiceNode/GatewayNode may call back into a
        # public engine API (fn(engine, inst)), whose exit would flush
        # WHILE the outer frame still owns the state RLock — acquiring the
        # flush lock there inverts the flush->state lock order (AB-BA
        # deadlock against a concurrent flusher) and would deliver to the
        # sink under the state lock. The outermost frame flushes instead.
        # (_is_owned is RLock private API, stable across CPython.)
        if self._lock._is_owned():
            return
        with self._audit_flush_lock:
            with self._lock:
                events = self._audit_buffer
                self._audit_buffer = []
            if not events:
                return
            batch_fn = getattr(self._audit, "batch", None)
            if callable(batch_fn):
                try:
                    batch_fn(events)
                except Exception:  # noqa: BLE001 - never break the flow
                    import logging

                    logging.getLogger(__name__).exception("audit sink failed")
                    return  # undelivered: retention cap remains the evictor
                self._evict_flushed(events)
                return
            delivered: list[dict[str, Any]] = []
            for ev in events:
                try:
                    self._audit(ev)
                    delivered.append(ev)
                except Exception:  # noqa: BLE001 - drop THIS event only
                    import logging

                    logging.getLogger(__name__).exception("audit sink failed")
            self._evict_flushed(delivered)

    def _evict_flushed(self, events: list[dict[str, Any]]) -> None:
        """Evict instances whose terminal audit event just reached the sink
        (audit-coupled eviction — see __init__). Caller holds the flush
        lock, NOT the state lock; lock order matches shutdown()."""
        if not self._audit_evict:
            return
        pids = [ev["pid"] for ev in events
                if ev.get("event") == "process_completed"]
        if not pids:
            return
        with self._lock:
            for pid in pids:
                inst = self._instances.get(pid)
                if inst is None or inst.status == "active":
                    continue  # re-driven/rolled-back pid live again: keep
                self._instances.pop(pid, None)
                for tid in self._tasks_by_pid.pop(pid, ()):
                    self._tasks.pop(tid, None)
                # the pid stays in _completed_order; the FIFO backstop's
                # pop(None) tolerates already-evicted entries

    @property
    def state_lock(self) -> threading.RLock:
        """The lock guarding instance/task state. External viewers (the REST
        server) hold it while serializing ``vars`` dicts — the engine mutates
        them in place, and iterating a live dict during a signal races."""
        return self._lock

    # -- definitions ------------------------------------------------------
    def definitions(self) -> tuple[str, ...]:
        """Registered process-definition ids (the router validates its rule
        base against these at wiring time)."""
        with self._lock:
            return tuple(self._definitions)

    def register(self, definition: ProcessDefinition) -> None:
        self._definitions[definition.id] = definition
        chain = self._straight_through_chain(definition)
        if chain is not None:
            self._static_chains[definition.id] = chain
        else:
            self._static_chains.pop(definition.id, None)

    @staticmethod
    def _straight_through_chain(
        definition: ProcessDefinition,
    ) -> tuple[list[ServiceNode], EndNode, list[str]] | None:
        """ServiceNode* -> EndNode with no branches? Then the node walk is
        static and the batch start path can skip per-node dispatch."""
        services: list[ServiceNode] = []
        history: list[str] = []
        name = definition.start
        for _ in range(len(definition.nodes) + 1):
            node = definition.nodes[name]
            history.append(name)
            if isinstance(node, ServiceNode):
                services.append(node)
                name = node.next
            elif isinstance(node, EndNode):
                return services, node, history
            else:
                return None
        return None  # cycle of service nodes: not straight-through

    def _check_alive(self) -> None:
        """Caller holds the lock. A decommissioned engine must refuse
        mutation: after a crash-recovery swap (runtime/recovery.py), a
        caller that raced the swap — e.g. a router scoring batch that was
        in flight past the pause timeout — would otherwise write starts
        and arm timers on the abandoned object. Refusing converts that
        into the router's normal engine-unreachable error path, and the
        rewound bus re-delivers the records to the live engine."""
        if self._dead:
            raise RuntimeError("engine is shut down (crash-recovery swap)")

    # -- public API (KIE-server-shaped: start / signal / tasks) -----------
    def start_process(self, def_id: str, variables: Mapping[str, Any]) -> int:
        try:
            with self._lock:
                self._check_alive()
                d = self._definitions[def_id]
                inst = Instance(
                    pid=next(self._pid), definition=d, vars=dict(variables)
                )
                self._instances[inst.pid] = inst
                self._started.inc(labels={"process": def_id})
                if self._audit is not None:
                    self._emit("process_started", inst.pid, def_id)
                self._run_from(inst, d.start)
                return inst.pid
        finally:
            # finally, not fallthrough: a raising service node documented
            # to propagate must still get its buffered events delivered
            self._flush_audit()

    # capability flag the router reads through any method proxy (fault
    # injector / breaker guard): this engine's start_process_batch accepts
    # ``copy_vars=False``. Remote clients (EngineRestClient) lack it.
    start_batch_nocopy = True

    def start_process_batch(
        self, def_id: str, variables_list: Sequence[Mapping[str, Any]],
        copy_vars: bool = True,
    ) -> list[int | None]:
        """Start many instances of one definition under a single lock
        acquisition — the router's hot path (one start per scored
        transaction, reference README.md:552) would otherwise pay a lock
        round-trip and per-label counter bump per transaction.

        Straight-through definitions (a ServiceNode chain into an EndNode —
        the "standard" process) additionally skip per-node dispatch: the
        node walk is precomputed at ``register`` time and the metrics
        counters advance once per batch instead of once per instance.

        ``copy_vars=False`` adopts each (plain-dict) variables mapping as
        the instance's vars WITHOUT the defensive copy — for callers that
        hand over freshly built, never-reused dicts (the router's route
        stage builds one per transaction and drops it). The copy was one
        of the larger constants in the GIL-bound hand-off, which bounds
        the parallel router fan-out's scaling. Non-dict mappings are
        still copied (and non-mappings still poison only their slot).

        Error semantics (unlike single ``start_process``, which propagates):
        an exception from a service/gateway aborts THAT instance only — its
        slot in the returned list is ``None``, the instance is left
        ``aborted``, and the rest of the batch still starts. One poisoned
        transaction must not drop a whole micro-batch of process starts.
        """
        try:
            return self._start_process_batch_locked(
                def_id, variables_list, copy_vars)
        finally:
            self._flush_audit()

    def _start_process_batch_locked(
        self, def_id: str, variables_list: Sequence[Mapping[str, Any]],
        copy_vars: bool = True,
    ) -> list[int | None]:
        with self._lock:
            self._check_alive()
            d = self._definitions[def_id]
            chain = self._static_chains.get(def_id)
            pids: list[int | None] = []
            audit_on = self._audit is not None
            if chain is None:
                for variables in variables_list:
                    try:
                        # a non-mapping element must poison only its slot:
                        # dict() belongs inside the isolation boundary too
                        inst = Instance(
                            pid=next(self._pid), definition=d,
                            vars=(variables
                                  if not copy_vars and type(variables) is dict
                                  else dict(variables)),
                        )
                    except (TypeError, ValueError):
                        pids.append(None)
                        continue
                    self._instances[inst.pid] = inst
                    self._started.inc(labels={"process": def_id})
                    if audit_on:
                        self._emit("process_started", inst.pid, def_id)
                    try:
                        self._run_from(inst, d.start)
                    except Exception:
                        inst.status = "aborted"
                        if audit_on:
                            self._emit("process_completed", inst.pid, def_id,
                                       status="aborted")
                        self._note_completed(inst.pid)
                        pids.append(None)
                        continue
                    pids.append(inst.pid)
            else:
                # straight-through fast lane. This loop is the engine's
                # per-transaction floor under the parallel router fan-out
                # (GIL-bound, one iteration per scored transaction at wire
                # rate): locals are hoisted, the clock is read once per
                # batch, and per-instance counter bumps are batched below.
                services, end, history = chain
                n_ok = 0
                n_started = 0
                now = self.clock.now()
                instances = self._instances
                next_pid = self._pid.__next__
                end_name = end.name
                end_status = end.status
                append_pid = pids.append
                for variables in variables_list:
                    try:
                        inst = Instance(
                            pid=next_pid(), definition=d,
                            vars=(variables
                                  if not copy_vars and type(variables) is dict
                                  else dict(variables)),
                        )
                    except (TypeError, ValueError):
                        append_pid(None)
                        continue
                    instances[inst.pid] = inst
                    n_started += 1
                    if audit_on:
                        self._emit("process_started", inst.pid, def_id)
                    try:
                        for si, svc in enumerate(services):
                            inst.node = svc.name
                            svc.fn(self, inst)
                    except Exception:
                        inst.history = list(history[: si + 1])
                        inst.status = "aborted"
                        if audit_on:
                            self._emit("process_completed", inst.pid, def_id,
                                       status="aborted")
                        self._note_completed(inst.pid, now)
                        append_pid(None)
                        continue
                    inst.node = end_name
                    inst.history = list(history)
                    inst.status = end_status
                    if audit_on:
                        self._emit("process_completed", inst.pid, def_id,
                                   status=end_status)
                    append_pid(inst.pid)
                    self._note_completed(inst.pid, now)
                    n_ok += 1
                if n_started:
                    self._started.inc(n_started, labels={"process": def_id})
                if n_ok:
                    self._completed.inc(
                        n_ok, labels={"process": def_id, "status": end.status}
                    )
        return pids

    def signal(self, pid: int, name: str, payload: Any = None) -> bool:
        """Deliver a signal; returns True iff it was consumed by a wait."""
        try:
            with self._lock:
                self._check_alive()
                inst = self._instances.get(pid)
                if (
                    inst is None
                    or inst.status != "active"
                    or inst.wait_signal != name
                ):
                    return False
                node = inst.definition.nodes[inst.node]
                assert isinstance(node, EventNode)
                self._consume_wait(inst)
                inst.vars["signal_payload"] = payload
                if self._audit is not None:
                    self._emit("signal", pid, inst.definition.id, name=name)
                self._run_from(inst, node.on_signal)
                return True
        finally:
            self._flush_audit()

    def instance(self, pid: int) -> Instance:
        with self._lock:
            return self._instances[pid]

    def completed_info(self, pid: int) -> dict[str, Any] | None:
        """Post-mortem summary for an evicted (or still-resident) completed
        instance, from the bounded ring; None if it aged out."""
        with self._lock:
            row = self._postmortem.get(pid)
        if row is None:
            return None
        return {"pid": pid, "process": row[0], "status": row[1],
                "ts": row[2]}

    def recent_completions(self, n: int = 100) -> list[dict[str, Any]]:
        with self._lock:
            tail = list(self._postmortem.items())[-n:]
        return [{"pid": pid, "process": row[0], "status": row[1],
                 "ts": row[2]} for pid, row in tail]

    def object_counts(self) -> dict[str, int]:
        """Live container sizes — the per-component object gauges the
        memory-drift hunt reads (metrics/exporter.py /memory)."""
        with self._lock:
            return {
                "instances": len(self._instances),
                "tasks": len(self._tasks),
                "completed_order": len(self._completed_order),
                "postmortem": len(self._postmortem),
                "audit_buffer": len(self._audit_buffer),
            }

    def instances(self, status: str | None = None) -> list[Instance]:
        with self._lock:
            return [
                i
                for i in self._instances.values()
                if status is None or i.status == status
            ]

    def tasks(self, status: str = "open") -> list[Task]:
        with self._lock:
            return [t for t in self._tasks.values() if t.status == status]

    def task(self, task_id: int) -> Task:
        with self._lock:
            return self._tasks[task_id]

    def complete_task(self, task_id: int, outcome: Any) -> None:
        try:
            with self._lock:
                self._check_alive()
                t = self._tasks[task_id]
                if t.status != "open":
                    raise ValueError(f"task {task_id} already {t.status}")
                t.status = "completed"
                t.outcome = outcome
                inst = self._instances[t.pid]
                node = inst.definition.nodes[inst.node]
                assert isinstance(node, UserTaskNode)
                inst.vars["task_outcome"] = outcome
                if self._audit is not None:
                    self._emit("task_completed", t.pid, inst.definition.id,
                               task_id=t.task_id, by="human", outcome=outcome)
                self._run_from(inst, node.next)
        finally:
            self._flush_audit()
        if self.task_listener is not None:
            try:
                self.task_listener(t)
            except Exception:  # noqa: BLE001
                # the task is already completed and the process advanced; a
                # broken observer (bad feature value, training failure) must
                # not surface as a failed complete_task to the investigator
                import logging

                logging.getLogger(__name__).exception(
                    "task listener failed for task %d", t.task_id
                )

    # -- persistence (jBPM keeps process state in its engine store;
    #    SURVEY.md §5 "jBPM process state (persistent in the engine)") ----
    def snapshot(self, include_completed: bool = False,
                 validate: bool = True) -> dict[str, Any]:
        """Serializable engine state: instances, tasks, id counters.

        ``validate=False`` skips the JSON round-trip at the end — for the
        checkpoint coordinator, which holds the router's pause barrier
        across this call and validates AFTER releasing it (at 50k live
        instances the round-trip is ~70% of the 600 ms snapshot, all of
        it needlessly inside the barrier). Every mutable container is
        still detached under the lock either way.

        Timer waits serialize as *remaining* seconds (clock epochs differ
        across processes). Process vars must be JSON-able — the same
        contract jBPM puts on persisted process variables.

        By default only ACTIVE instances and their open tasks are captured
        (jBPM likewise drops completed instances from the runtime store,
        keeping history in the audit log — here, in metrics): a long-running
        pipeline starts a process per flagged transaction, and snapshotting
        every completed instance forever would grow the state file and the
        save/restore cost without bound.
        """
        with self._lock:
            now = self.clock.now()
            live = {
                pid
                for pid, i in self._instances.items()
                if include_completed or i.status == "active"
            }
            instances = []
            for i in self._instances.values():
                if i.pid not in live:
                    continue
                instances.append(
                    {
                        "pid": i.pid,
                        "def": i.definition.id,
                        "vars": _copy_containers(i.vars),
                        "status": i.status,
                        "node": i.node,
                        "wait_signal": i.wait_signal,
                        "wait_gen": i.wait_gen,
                        "timer_remaining_s": (
                            None
                            if i.timer_deadline is None
                            else max(0.0, i.timer_deadline - now)
                        ),
                        "history": list(i.history),
                    }
                )
            tasks = [
                {
                    "task_id": t.task_id,
                    "pid": t.pid,
                    "name": t.name,
                    "vars": _copy_containers(t.vars),
                    "status": t.status,
                    "suggested_outcome": t.suggested_outcome,
                    "prediction_confidence": t.prediction_confidence,
                    "outcome": t.outcome,
                }
                for t in self._tasks.values()
                if t.pid in live and (include_completed or t.status == "open")
            ]
            snap = {
                "version": 1,
                "next_pid": next(self._pid),
                "next_tid": next(self._tid),
                "instances": instances,
                "tasks": tasks,
            }
            # the counters advanced to produce the snapshot; keep going from
            # the recorded values so live allocation stays consistent
            self._pid = itertools.count(snap["next_pid"])
            self._tid = itertools.count(snap["next_tid"])
        # JSON round-trip OUTSIDE the lock: the platform's checkpoint loop
        # calls snapshot() every few seconds, and serializing every live
        # instance while holding the lock would periodically stall
        # start_process/signal/complete_task for time proportional to the
        # active-instance count. ``_copy_containers`` above already detached
        # every mutable JSON container under the lock (so even ServiceNodes
        # that mutate nested vars can't tear this), and the round-trip here
        # validates serializability now, not at restore time months later.
        if not validate:
            return snap
        return json.loads(json.dumps(snap))

    def restore(self, snap: Mapping[str, Any]) -> None:
        """Load a snapshot into an empty engine and re-arm pending timers.

        Definitions are code, not data (like jBPM KJARs): every definition
        referenced by the snapshot must already be ``register``-ed. Waits
        whose timers expired while the engine was down are re-armed with
        zero delay — the timeout path fires promptly after restore, which
        is jBPM's overdue-timer recovery behavior.
        """
        if snap.get("version") != 1:
            raise ValueError(f"unknown snapshot version {snap.get('version')!r}")
        with self._lock:
            if self._instances or self._tasks:
                raise ValueError("restore requires an empty engine")
            missing = {i["def"] for i in snap["instances"]} - set(self._definitions)
            if missing:
                raise ValueError(f"snapshot needs unregistered definitions {sorted(missing)}")
            # definitions are code and may have drifted since the snapshot:
            # an instance parked on a renamed node would pass restore and
            # then KeyError at signal/timer time, wedging it permanently —
            # fail here, with names
            for s in snap["instances"]:
                d = self._definitions[s["def"]]
                if s["status"] == "active" and s["node"] not in d.nodes:
                    raise ValueError(
                        f"instance {s['pid']}: node {s['node']!r} no longer in "
                        f"definition {d.id!r} (has {sorted(d.nodes)})"
                    )
                if s["status"] == "active" and s["wait_signal"] is not None:
                    node = d.nodes[s["node"]]
                    if not isinstance(node, EventNode) or node.signal != s["wait_signal"]:
                        raise ValueError(
                            f"instance {s['pid']}: waiting on signal "
                            f"{s['wait_signal']!r} but node {s['node']!r} is not "
                            f"an EventNode for it"
                        )
            for s in snap["instances"]:
                inst = Instance(
                    pid=int(s["pid"]),
                    definition=self._definitions[s["def"]],
                    vars=dict(s["vars"]),
                    status=s["status"],
                    node=s["node"],
                    wait_signal=s["wait_signal"],
                    wait_gen=int(s["wait_gen"]),
                    history=list(s["history"]),
                )
                self._instances[inst.pid] = inst
                if inst.status != "active":
                    self._completed_order.append(inst.pid)
            for s in snap["tasks"]:
                t = Task(
                    task_id=int(s["task_id"]),
                    pid=int(s["pid"]),
                    name=s["name"],
                    vars=dict(s["vars"]),
                    status=s["status"],
                    suggested_outcome=s["suggested_outcome"],
                    prediction_confidence=s["prediction_confidence"],
                    outcome=s["outcome"],
                )
                self._tasks[t.task_id] = t
                self._tasks_by_pid.setdefault(t.pid, []).append(t.task_id)
            self._pid = itertools.count(int(snap["next_pid"]))
            self._tid = itertools.count(int(snap["next_tid"]))
            # re-arm after all state is in place: a zero-delay timer may
            # fire (RealClock scheduler thread) as soon as we release _lock
            for s in snap["instances"]:
                remaining = s["timer_remaining_s"]
                if s["status"] == "active" and remaining is not None:
                    inst = self._instances[int(s["pid"])]
                    inst.timer_deadline = self.clock.now() + remaining
                    inst.timer = self.clock.call_later(
                        remaining,
                        lambda pid=inst.pid, g=inst.wait_gen: self._timer_fired(pid, g),
                    )

    def shutdown(self) -> None:
        """Decommission this engine object after a crash-recovery swap.

        The recovery coordinator (runtime/recovery.py) abandons the live
        engine and replaces it with a snapshot-restored one; without this,
        the abandoned object's already-scheduled timer callbacks would
        keep firing — mutating dead state and, worse, emitting post-epoch
        audit events through the SHARED bus sink, corrupting the stream's
        epoch accounting.  Cancels every pending timer, drops buffered
        audit events, and silences the sink.  Lock order matches
        ``_flush_audit`` (flush lock, then state lock), so an in-flight
        flush completes its delivery before the shutdown lands — after
        return, nothing more reaches the sink."""
        with self._audit_flush_lock:
            with self._lock:
                self._dead = True
                for inst in self._instances.values():
                    if inst.timer is not None:
                        inst.timer.cancel()
                        inst.timer = None
                self._audit_buffer.clear()
                self._audit = None

    def save(self, path: str) -> None:
        """Checksummed atomic snapshot-to-file (tmp + fsync + rename with
        generation retention, runtime/durability.py)."""
        from ccfd_tpu.runtime.durability import write_json_artifact

        write_json_artifact(path, self.snapshot(),
                            artifact="engine_snapshot")

    def load(self, path: str) -> None:
        """Verified restore: a corrupt snapshot quarantines and the
        last-good retained generation loads instead."""
        from ccfd_tpu.runtime.durability import read_json_artifact

        self.restore(read_json_artifact(path, artifact="engine_snapshot"))

    # -- internals --------------------------------------------------------
    def _note_completed(self, pid: int, now: float | None = None) -> None:
        """Record a terminal instance and evict past the retention cap.
        Caller holds the lock (``now`` lets batch callers amortize the
        clock read). Evicted instances (and their tasks) leave the
        runtime store; history lives on in the audit stream and metrics,
        like jBPM's audit log vs runtime separation. With an audit sink the
        real eviction happens in ``_evict_flushed`` (as soon as the
        terminal event is delivered); the FIFO here is the no-sink path
        and the backstop for sink failures."""
        inst = self._instances.get(pid)
        if inst is not None and self._audit is not None:
            # bounded post-mortem ring: a tuple summary outlives the
            # audit-coupled eviction (tuples, not dicts: this runs once
            # per completed transaction at wire rate; completed_info
            # rebuilds the dict on query). Without an audit sink there is
            # no prompt eviction — the completed-retention FIFO keeps the
            # full instance queryable — so the ring would be pure hot-path
            # overhead and is skipped.
            pm = self._postmortem
            pm[pid] = (inst.definition.id, inst.status,
                       self.clock.now() if now is None else now)
            if len(pm) > self._postmortem_retention:
                del pm[next(iter(pm))]
        self._completed_order.append(pid)
        while len(self._completed_order) > self._completed_retention:
            old = self._completed_order.popleft()
            self._instances.pop(old, None)
            for tid in self._tasks_by_pid.pop(old, ()):
                self._tasks.pop(tid, None)

    def _consume_wait(self, inst: Instance) -> None:
        inst.wait_signal = None
        inst.wait_gen += 1
        inst.timer_deadline = None
        if inst.timer is not None:
            inst.timer.cancel()
            inst.timer = None

    def _timer_fired(self, pid: int, gen: int) -> None:
        try:
            with self._lock:
                inst = self._instances.get(pid)
                if (
                    self._dead
                    or inst is None
                    or inst.status != "active"
                    or inst.wait_signal is None
                    or inst.wait_gen != gen
                ):
                    return  # a signal won the race; timer is a no-op
                node = inst.definition.nodes[inst.node]
                assert isinstance(node, EventNode)
                self._consume_wait(inst)
                if self._audit is not None:
                    self._emit("timer_fired", pid, inst.definition.id,
                               node=inst.node)
                self._run_from(inst, node.on_timeout)
        finally:
            self._flush_audit()

    def _run_from(self, inst: Instance, node_name: str) -> None:
        """Advance the instance until it blocks (event/user task) or ends."""
        while True:
            node = inst.definition.nodes[node_name]
            inst.node = node_name
            inst.history.append(node_name)
            if isinstance(node, ServiceNode):
                node.fn(self, inst)
                node_name = node.next
            elif isinstance(node, GatewayNode):
                node_name = node.choose(self, inst)
                if node_name not in inst.definition.nodes:
                    raise ValueError(
                        f"{inst.definition.id}:{node.name} chose unknown node "
                        f"{node_name!r}"
                    )
            elif isinstance(node, EventNode):
                timeout = (
                    node.timeout_s(inst) if callable(node.timeout_s) else node.timeout_s
                )
                inst.wait_signal = node.signal
                gen = inst.wait_gen
                inst.timer_deadline = self.clock.now() + timeout
                inst.timer = self.clock.call_later(
                    timeout, lambda pid=inst.pid, g=gen: self._timer_fired(pid, g)
                )
                return
            elif isinstance(node, UserTaskNode):
                task = Task(
                    task_id=next(self._tid),
                    pid=inst.pid,
                    name=node.task_name,
                    vars=dict(inst.vars),
                )
                self._tasks[task.task_id] = task
                self._tasks_by_pid.setdefault(inst.pid, []).append(task.task_id)
                if self._audit is not None:
                    self._emit("task_created", inst.pid, inst.definition.id,
                               task_id=task.task_id, name=node.task_name)
                if self.prediction_service is not None:
                    outcome, confidence = self.prediction_service.predict(task)
                    task.prediction_confidence = confidence
                    if confidence >= self.confidence_threshold:
                        # jBPM semantics: auto-close the task (README.md:580)
                        task.status = "completed"
                        task.outcome = outcome
                        inst.vars["task_outcome"] = outcome
                        inst.vars["task_auto_completed"] = True
                        if self._audit is not None:
                            self._emit(
                                "task_completed", inst.pid,
                                inst.definition.id, task_id=task.task_id,
                                by="prediction_service", outcome=outcome,
                            )
                        node_name = node.next
                        continue
                    task.suggested_outcome = outcome  # pre-fill only (README.md:581)
                return
            elif isinstance(node, EndNode):
                inst.status = node.status
                self._completed.inc(
                    labels={"process": inst.definition.id, "status": node.status}
                )
                if self._audit is not None:
                    self._emit("process_completed", inst.pid,
                               inst.definition.id, status=node.status)
                self._note_completed(inst.pid)
                return
            else:  # pragma: no cover
                raise TypeError(f"unknown node type {type(node)}")
