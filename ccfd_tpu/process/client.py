"""REST client for a remote process engine (the router's KIE_SERVER_URL hop).

The reference router drives the KIE server over HTTP
(``KIE_SERVER_URL``, reference deploy/router.yaml:63-64): process starts
for scored transactions and signal forwarding for customer responses.
This client implements the in-process ``EngineClient`` protocol
(ccfd_tpu/router/router.py) against ccfd_tpu/process/server.py, so the
router can run on the TPU host while the engine lives elsewhere. Pooled
connections + bounded retries, mirroring ccfd_tpu/serving/client.py.
"""

from __future__ import annotations

import http.client
import json
import queue
import urllib.parse
from typing import Any, Mapping


class EngineRestClient:
    def __init__(
        self,
        base_url: str,
        pool_size: int = 4,
        timeout_s: float = 5.0,
        retries: int = 2,
    ):
        u = urllib.parse.urlparse(base_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"unsupported scheme in KIE_SERVER_URL: {base_url!r}")
        self._host = u.hostname or "localhost"
        self._port = u.port or 8090
        self._timeout = timeout_s
        self._retries = max(0, retries)
        self._pool: "queue.Queue[http.client.HTTPConnection]" = queue.Queue()
        for _ in range(max(1, pool_size)):
            self._pool.put(self._connect())

    def _connect(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self._timeout
        )

    def _request(
        self, method: str, path: str, body: Any = None, idempotent: bool = True
    ) -> tuple[int, Any]:
        payload = json.dumps(body).encode() if body is not None else None
        last_exc: Exception | None = None
        for _ in range(self._retries + 1):
            conn = self._pool.get()
            try:
                conn.request(
                    method, path, body=payload,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = resp.read()
                self._pool.put(conn)
                return resp.status, (json.loads(data) if data else None)
            except (OSError, http.client.HTTPException) as e:
                last_exc = e
                conn.close()
                self._pool.put(self._connect())
                # a non-idempotent request (start_process) may have reached
                # the engine before the failure — blind retry would start a
                # duplicate instance. Only a refused connection proves the
                # request never arrived.
                if not idempotent and not isinstance(e, ConnectionRefusedError):
                    break
        raise ConnectionError(
            f"engine at {self._host}:{self._port} unreachable: {last_exc}"
        )

    # -- EngineClient protocol --------------------------------------------
    def start_process(self, def_id: str, variables: Mapping[str, Any]) -> int:
        code, body = self._request(
            "POST", f"/rest/processes/{def_id}/instances",
            {"variables": dict(variables)},
            idempotent=False,
        )
        if code != 201:
            raise RuntimeError(f"start_process {def_id!r} failed: {code} {body}")
        return int(body["process_id"])

    def signal(self, pid: int, name: str, payload: Any = None) -> bool:
        code, body = self._request(
            "POST", f"/rest/instances/{pid}/signal/{name}", {"payload": payload}
        )
        return code == 200 and bool(body.get("consumed"))

    # -- convenience (investigator tooling) -------------------------------
    def instance(self, pid: int) -> Mapping[str, Any]:
        code, body = self._request("GET", f"/rest/instances/{pid}")
        if code != 200:
            raise KeyError(pid)
        return body

    def tasks(self, status: str = "open") -> list[Mapping[str, Any]]:
        code, body = self._request("GET", f"/rest/tasks?status={status}")
        if code != 200:
            raise RuntimeError(f"tasks query failed: {code} {body}")
        return body or []

    def complete_task(self, task_id: int, outcome: Any) -> None:
        code, body = self._request(
            "POST", f"/rest/tasks/{task_id}/complete", {"outcome": outcome}
        )
        if code != 200:
            raise RuntimeError(f"complete_task {task_id} failed: {code} {body}")
