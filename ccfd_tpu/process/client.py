"""REST client for a remote process engine (the router's KIE_SERVER_URL hop).

The reference router drives the KIE server over HTTP
(``KIE_SERVER_URL``, reference deploy/router.yaml:63-64): process starts
for scored transactions and signal forwarding for customer responses.
This client implements the in-process ``EngineClient`` protocol
(ccfd_tpu/router/router.py) against ccfd_tpu/process/server.py, so the
router can run on the TPU host while the engine lives elsewhere. Pooled
connections + bounded retries, mirroring ccfd_tpu/serving/client.py.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

from ccfd_tpu.utils.httpclient import PooledHTTPClient


class EngineRestClient:
    def __init__(
        self,
        base_url: str,
        pool_size: int = 4,
        timeout_s: float = 5.0,
        retries: int = 2,
        breaker=None,
        faults=None,
        tracer=None,
    ):
        # breaker/faults ride the shared transport (utils/httpclient.py):
        # an open circuit on the engine hop refuses instantly — the router
        # counts the group as start errors and keeps routing instead of
        # stalling a full timeout per micro-batch. tracer: every engine
        # RPC becomes a client span with traceparent injection, so the
        # EngineServer side joins the router's trace.
        self._http = PooledHTTPClient(
            base_url, default_port=8090, pool_size=pool_size,
            timeout_s=timeout_s, retries=retries,
            scheme_error="unsupported scheme in KIE_SERVER_URL",
            breaker=breaker, faults=faults,
            tracer=tracer, trace_edge="engine",
        )

    def _request(
        self, method: str, path: str, body: Any = None, idempotent: bool = True
    ) -> tuple[int, Any]:
        # non-idempotent start_process must not blind-retry after the request
        # may have reached the engine — a re-send would start a duplicate
        # instance (retry policy lives in PooledHTTPClient)
        return self._http.request(method, path, body, idempotent=idempotent)

    # -- EngineClient protocol --------------------------------------------
    def start_process(self, def_id: str, variables: Mapping[str, Any]) -> int:
        code, body = self._request(
            "POST", f"/rest/processes/{def_id}/instances",
            {"variables": dict(variables)},
            idempotent=False,
        )
        if code != 201:
            raise RuntimeError(f"start_process {def_id!r} failed: {code} {body}")
        return int(body["process_id"])

    def start_process_batch(
        self, def_id: str, variables_list: Sequence[Mapping[str, Any]]
    ) -> list[int | None]:
        """One HTTP round-trip for a micro-batch of process starts (the
        router's hot path). ``None`` slots are instances the engine aborted
        on a service-node error; a transport failure raises instead."""
        code, body = self._request(
            "POST", f"/rest/processes/{def_id}/instances/batch",
            {"variables_list": [dict(v) for v in variables_list]},
            idempotent=False,
        )
        if code != 201:
            raise RuntimeError(f"start_process_batch {def_id!r} failed: {code} {body}")
        return [None if p is None else int(p) for p in body["process_ids"]]

    def signal(self, pid: int, name: str, payload: Any = None) -> bool:
        code, body = self._request(
            "POST", f"/rest/instances/{pid}/signal/{name}", {"payload": payload}
        )
        return code == 200 and bool(body.get("consumed"))

    # -- convenience (investigator tooling) -------------------------------
    def instance(self, pid: int) -> Mapping[str, Any]:
        code, body = self._request("GET", f"/rest/instances/{pid}")
        if code != 200:
            raise KeyError(pid)
        return body

    def tasks(self, status: str = "open") -> list[Mapping[str, Any]]:
        code, body = self._request("GET", f"/rest/tasks?status={status}")
        if code != 200:
            raise RuntimeError(f"tasks query failed: {code} {body}")
        return body or []

    def complete_task(self, task_id: int, outcome: Any) -> None:
        code, body = self._request(
            "POST", f"/rest/tasks/{task_id}/complete", {"outcome": outcome}
        )
        if code != 200:
            raise RuntimeError(f"complete_task {task_id} failed: {code} {body}")
