"""Pure fleet-protocol functions: membership, ownership, parity, accounting.

Everything here is plain-Python and deterministic — no jax, no sockets, no
clocks read internally (callers pass ``now``) — so the fleet protocol's
decision logic is CI-gated by fast tier-1 unit tests
(tests/test_fleet_protocol.py) without spawning a single process. The
fleet member (fleet/member.py), the supervisor (fleet/supervisor.py), the
drills (tools/fleet_drill.py, tools/fleet_smoke.py) and the multihost
drill (tools/multihost_drill.py) all call these instead of re-deriving
the invariants inline.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping

LOCAL_ROWS_DEFAULT = 512


# -- membership ------------------------------------------------------------
def live_members(last_seen: Mapping[str, float], now: float,
                 ttl_s: float) -> list[str]:
    """Members whose last heartbeat is within the lease window, sorted.

    The lease model: a heartbeat at time t grants a lease until
    ``t + ttl_s``; a member whose lease expired is DEAD to the protocol
    (its partitions are re-adopted, its admission share redistributed)
    even if the process still exists — exactly Kafka's session timeout,
    and the reason the bus-side epoch fence must exist: a deposed member
    may not know it is dead."""
    return sorted(m for m, t in last_seen.items() if now - t <= ttl_s)


def elect_aggregator(members: Iterable[str]) -> str | None:
    """Deterministic aggregator election: lexicographically first live
    member. Every member computes this locally from the same membership
    view — no ballot, no coordinator; a split view heals on the next
    gossip round (both claimants export, scrapes dedupe by member label).
    None when the fleet is empty."""
    members = sorted(members)
    return members[0] if members else None


# -- partition ownership ---------------------------------------------------
def plan_partition_assignment(members: Iterable[str],
                              n_partitions: int) -> dict[int, str]:
    """Deterministic round-robin plan: partition p -> sorted-member
    p % len(members). This is the PLANNED ownership used for gauges and
    drill assertions; the bus's consumer-group rebalance is the
    authoritative assignment (same round-robin shape, but over join
    order). Empty members -> empty plan (no owner, nothing served)."""
    ms = sorted(members)
    if not ms:
        return {}
    return {p: ms[p % len(ms)] for p in range(int(n_partitions))}


def check_disjoint_ownership(owners: Mapping[str, Iterable[int]],
                             n_partitions: int) -> list[str]:
    """Validate a claimed ownership map ``{member: [partition, ...]}``:
    every partition in [0, n) owned by EXACTLY one member. Returns a list
    of human-readable violations (empty == invariant holds). Double
    ownership is the double-route precursor; an orphan partition is the
    drop precursor — the two failure modes the fleet drill exists to
    rule out."""
    violations: list[str] = []
    seen: dict[int, str] = {}
    for member in sorted(owners):
        for p in owners[member]:
            p = int(p)
            if p < 0 or p >= n_partitions:
                violations.append(
                    f"{member} claims out-of-range partition {p} "
                    f"(n_partitions={n_partitions})")
                continue
            if p in seen:
                violations.append(
                    f"partition {p} owned by both {seen[p]} and {member}")
            else:
                seen[p] = member
    for p in range(int(n_partitions)):
        if p not in seen:
            violations.append(f"partition {p} has no owner")
    return violations


# -- champion parity -------------------------------------------------------
def check_fingerprint_parity(fingerprints: Mapping[str, str | None]
                             ) -> dict[str, Any]:
    """Fleet-wide champion parity from ``{member: fingerprint | None}``.

    The majority fingerprint is the fleet champion (ties break
    lexicographically — deterministic, so every member quarantines the
    SAME side of a 50/50 split); members serving anything else are
    ``stale`` and must self-quarantine to the rules tier (fleet/member.py
    FleetParityGate). ``None`` fingerprints are ``unknown`` — a member
    that has not published yet is NOT stale (quarantining members during
    warm-up would flap the whole fleet at every cold start)."""
    known = {m: fp for m, fp in fingerprints.items() if fp}
    if not known:
        return {"majority": None, "stale": [], "unknown":
                sorted(fingerprints), "parity": True}
    counts: dict[str, int] = {}
    for fp in known.values():
        counts[fp] = counts.get(fp, 0) + 1
    majority = sorted(counts, key=lambda fp: (-counts[fp], fp))[0]
    stale = sorted(m for m, fp in known.items() if fp != majority)
    unknown = sorted(m for m, fp in fingerprints.items() if not fp)
    return {
        "majority": majority,
        "stale": stale,
        "unknown": unknown,
        "parity": not stale,
    }


# -- fleet accounting ------------------------------------------------------
def check_member_accounting(counters: Mapping[str, Mapping[str, int]]
                            ) -> list[str]:
    """Per-member conservation: incoming == routed + shed + errors, and
    the same law over the fleet-aggregated sums. ``counters`` maps
    ``{member: {incoming, routed, shed, errors}}``. Returns violations
    (empty == conserved). This is the scraped-counter view — it can only
    be asserted for members that are still alive to scrape; the durable
    per-tx view under a hard kill is ``check_ledger_conservation``."""
    violations: list[str] = []
    totals = {"incoming": 0, "routed": 0, "shed": 0, "errors": 0}
    for member in sorted(counters):
        c = counters[member]
        inc = int(c.get("incoming", 0))
        out = (int(c.get("routed", 0)) + int(c.get("shed", 0))
               + int(c.get("errors", 0)))
        for k in totals:
            totals[k] += int(c.get(k, 0))
        if inc != out:
            violations.append(
                f"{member}: incoming {inc} != routed+shed+errors {out}")
    agg_out = totals["routed"] + totals["shed"] + totals["errors"]
    if totals["incoming"] != agg_out:
        violations.append(
            f"fleet: incoming {totals['incoming']} != "
            f"routed+shed+errors {agg_out}")
    return violations


def check_ledger_conservation(
    produced: Iterable[str],
    ledger: Iterable[Mapping[str, Any]],
) -> dict[str, Any]:
    """Durable per-transaction conservation over the fleet ledger.

    ``produced`` is every transaction id sent into the bus; ``ledger``
    is the FleetLedgerTap stream — one entry per terminal disposition,
    each carrying ``tx``, ``member`` and the bus group ``epoch`` it was
    routed under. The law, under at-least-once delivery with an epoch
    fence:

      * no drop:  every produced tx has >= 1 disposition;
      * no ghost: every ledger tx was actually produced;
      * no same-epoch double-route: within one epoch each partition has
        exactly one owner, so a tx disposed twice under ONE epoch means
        the fence failed. Cross-epoch duplicates are legitimate
        at-least-once redeliveries (a fenced batch re-reading from the
        committed offset) — counted, never violations.
    """
    produced_set = set(produced)
    seen: dict[str, set[tuple[Any, Any]]] = {}
    same_epoch_dupes: list[str] = []
    epoch_routes: dict[tuple[str, Any], int] = {}
    for e in ledger:
        tx = str(e["tx"])
        seen.setdefault(tx, set()).add((e.get("member"), e.get("epoch")))
        key = (tx, e.get("epoch"))
        epoch_routes[key] = epoch_routes.get(key, 0) + 1
        if epoch_routes[key] == 2:  # report once per offending (tx, epoch)
            same_epoch_dupes.append(
                f"tx {tx} disposed {'>'}1x under epoch {e.get('epoch')}")
    dropped = sorted(produced_set - set(seen))
    ghosts = sorted(set(seen) - produced_set)
    redelivered = sum(1 for routes in seen.values() if len(
        {ep for _, ep in routes}) > 1)
    return {
        "produced": len(produced_set),
        "disposed": len(seen),
        "dropped": dropped,
        "ghosts": ghosts,
        "same_epoch_dupes": same_epoch_dupes,
        "cross_epoch_redeliveries": redelivered,
        "conserved": not dropped and not ghosts and not same_epoch_dupes,
    }


# -- admission shares ------------------------------------------------------
def admission_share(global_ceiling: int, n_live: int) -> int:
    """Per-member admission ceiling under the fleet-wide bound: an equal
    split of the global ceiling over live members, floor 1. N-1 survivors
    of a member death RAISE their share (they absorb the dead member's
    partitions and its traffic); a rejoin lowers it back."""
    return max(1, int(global_ceiling) // max(1, int(n_live)))


# -- multihost drill invariants (tools/multihost_drill.py) -----------------
def check_multihost_reports(
    reports: list[Mapping[str, Any]],
    n_processes: int,
    local_devices: int,
    model_parallel: int,
    local_rows: int = LOCAL_ROWS_DEFAULT,
) -> dict[str, bool]:
    """The multihost drill's per-topology invariants as a pure function
    over the child-process reports (tools/multihost_drill.py emits them,
    tier-1 tests exercise this logic directly — no jax.distributed
    needed). Caller guarantees ``len(reports) == n_processes > 0``."""
    rs = sorted(reports, key=lambda r: r["process_id"])
    r0 = rs[0]
    return {
        "counts": all(
            r["process_count"] == n_processes
            and r["global_devices"] == n_processes * local_devices
            and r["local_devices"] == local_devices
            for r in rs
        ),
        # different inputs per process...
        "distinct_inputs": len(
            {r["input_fingerprint"] for r in rs}) == n_processes,
        # ...yet identical replicated losses: the cross-process
        # all-reduce really happened, every step
        "losses_agree": all(r["losses"] == r0["losses"] for r in rs),
        "losses_finite": all(
            l == l and abs(l) != float("inf")
            for r in rs for l in r["losses"]
        ),
        "score_means_agree": all(
            r["score_mean"] == r0["score_mean"] for r in rs
        ),
        "global_batch": r0["global_batch"] == local_rows * n_processes,
        # exact attention over a ring whose edges cross the process
        # boundary: parity vs dense computed in the same jit
        "ring_crosses_processes": all(
            r["ring_positions"] == n_processes * local_devices
            // model_parallel for r in rs
        ),
        "ring_parity": all(
            r["ring_vs_dense_max_delta"] < 1e-4 for r in rs
        ),
        "ring_agree": len(
            {r["ring_vs_dense_max_delta"] for r in rs}) == 1,
    }
