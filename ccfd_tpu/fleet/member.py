"""FleetMember: one operator process's seat at the fleet table.

Each member of the fleet is a full ``platform.operator`` process sharing
ONE networked bus; this module adds the fleet-level planes on top of the
member's local ones:

* **membership** — a heartbeat HTTP endpoint (``GET /fleet/health``) and
  a gossip loop dialing every peer each tick. A peer whose lease
  (``ttl_s``) expires is DEAD to the protocol (protocol.live_members);
  unreachable peers are re-dialed under jittered exponential backoff
  (runtime/breaker.backoff_s) so a respawned member rejoins without a
  thundering herd.
* **fleet admission** — the local AIMD budget's ceiling is rescaled to
  an equal share of the fleet-wide ceiling over LIVE members
  (protocol.admission_share -> AdaptiveInflightBudget.rescale_ceiling):
  N-1 survivors of a kill absorb the dead member's share, a rejoin
  hands it back.
* **champion parity** — members exchange the PR 12 checkpoint
  fingerprint over the heartbeat; a member whose fingerprint diverges
  from the fleet majority self-quarantines to the rules tier through
  the router's heal-gate seam (:class:`FleetParityGate`, AND-composed
  with the storage/heal gates by the operator).
* **aggregation** — the lexicographically-first live member is the
  elected aggregator (protocol.elect_aggregator): its gauges are the
  fleet-true series for the Fleet board, and it alone dumps the
  member-kill FlightRecorder bundle (once per (member, incarnation))
  when a peer's lease expires.

Gauges: ``ccfd_fleet_members``, ``ccfd_fleet_epoch``,
``ccfd_fleet_partition_owner{partition}``, ``ccfd_fleet_parity``,
``ccfd_fleet_quarantined``, ``ccfd_fleet_aggregator``,
``ccfd_fleet_admission_ceiling``; counter
``fleet_member_kill_bundles_total``.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable, Iterable

from ccfd_tpu.fleet.protocol import (
    admission_share,
    check_fingerprint_parity,
    elect_aggregator,
    live_members,
)
from ccfd_tpu.runtime.breaker import backoff_s
from ccfd_tpu.utils.httpserver import FrameworkHTTPServer

log = logging.getLogger(__name__)

HEALTH_PATH = "/fleet/health"


class FleetParityGate:
    """Heal-gate-shaped quarantine switch for a stale-champion member.

    While quarantined BOTH tiers are refused — the host tier would
    forward the same stale params the device would, so the only honest
    fallback is rules-only (the same posture as the storage pin). The
    gossip loop flips it from parity evidence; the router consults it
    through the operator's ComposedHealGate chain.
    """

    def __init__(self, registry: Any = None):
        self._mu = threading.Lock()
        self._quarantined = False
        self.reason: str | None = None
        self._g = None
        if registry is not None:
            self._g = registry.gauge(
                "ccfd_fleet_quarantined",
                "1 while this member self-quarantined to the rules tier "
                "(champion fingerprint diverged from the fleet majority)",
            )
            self._g.set(0)

    @property
    def quarantined(self) -> bool:
        with self._mu:
            return self._quarantined

    def quarantine(self, reason: str) -> None:
        with self._mu:
            was = self._quarantined
            self._quarantined = True
            self.reason = reason
            if self._g is not None:
                self._g.set(1)
        if not was:
            log.error("fleet parity quarantine: %s", reason)

    def release(self) -> None:
        with self._mu:
            was = self._quarantined
            self._quarantined = False
            self.reason = None
            if self._g is not None:
                self._g.set(0)
        if was:
            log.warning("fleet parity quarantine released")

    # the router's heal-gate surface
    def device_allowed(self) -> bool:
        return not self.quarantined

    def host_allowed(self) -> bool:
        return not self.quarantined


class FleetMember:
    """Gossip + heartbeat + fleet actuators; see the module docstring.

    ``consumers_fn`` resolves the router's tx consumers (one for a
    single Router, one per worker under a ParallelRouter) so ownership
    and epoch track crash-recycled consumers instead of a stale
    snapshot. ``counters_fn`` returns the member's accounting counters
    (the operator wires it to the router registry totals).
    """

    def __init__(
        self,
        member: str,
        registry: Any,
        peers: Iterable[str] = (),
        heartbeat_host: str = "127.0.0.1",
        heartbeat_port: int = 0,
        ttl_s: float = 3.0,
        overload: Any = None,
        recorder: Any = None,
        fingerprint_fn: Callable[[], str | None] | None = None,
        consumers_fn: Callable[[], list] | None = None,
        counters_fn: Callable[[], dict[str, int]] | None = None,
        global_max_inflight: int | None = None,
        gossip_timeout_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.member = str(member)
        self.registry = registry
        self.peers = [p.rstrip("/") for p in peers]
        self.heartbeat_host = heartbeat_host
        self.heartbeat_port = int(heartbeat_port)
        self.ttl_s = float(ttl_s)
        self.overload = overload
        self.recorder = recorder
        self.fingerprint_fn = fingerprint_fn
        self.consumers_fn = consumers_fn
        self.counters_fn = counters_fn
        self._gossip_timeout_s = float(gossip_timeout_s)
        self._clock = clock
        # incarnation distinguishes a respawned member from its corpse:
        # the aggregator's member-kill bundle fires once per incarnation
        self.incarnation = f"{os.getpid()}-{int(clock() * 1000) & 0xFFFFFF}"
        self.parity_gate = FleetParityGate(registry)
        if overload is not None:
            budget = overload.budget
            self._global_ceiling = int(global_max_inflight
                                       or budget.max_limit)
        else:
            self._global_ceiling = int(global_max_inflight or 0)
        self._mu = threading.Lock()
        self._last_seen: dict[str, float] = {}
        self._fingerprints: dict[str, str | None] = {}
        self._incarnations: dict[str, str] = {}
        self._peer_health: dict[str, dict] = {}
        self._peer_clients: dict[str, Any] = {}
        self._peer_attempts: dict[str, int] = {}
        self._peer_next_dial: dict[str, float] = {}
        self._reported_kills: set[tuple[str, str]] = set()
        self._prev_live: set[str] = set()
        self._prev_owned: set[int] = set()
        self._rng = random.Random(hash(self.member) & 0xFFFF)
        self._stop = threading.Event()
        self._httpd: FrameworkHTTPServer | None = None
        r = registry
        self._g_members = r.gauge(
            "ccfd_fleet_members", "live fleet members (lease not expired)")
        self._g_epoch = r.gauge(
            "ccfd_fleet_epoch",
            "this member's view of the router group's bus epoch")
        self._g_owner = r.gauge(
            "ccfd_fleet_partition_owner",
            "1 for each tx partition this member currently owns "
            "(fleet-wide sum per partition must be exactly 1)")
        self._g_parity = r.gauge(
            "ccfd_fleet_parity",
            "1 while every live member with a known fingerprint serves "
            "the fleet-majority champion")
        self._g_aggregator = r.gauge(
            "ccfd_fleet_aggregator",
            "1 on the elected aggregator member (lexicographically first "
            "live member)")
        self._g_share = r.gauge(
            "ccfd_fleet_admission_ceiling",
            "this member's share of the fleet-wide admission ceiling")
        self._c_kills = r.counter(
            "fleet_member_kill_bundles_total",
            "member-kill incident bundles dumped by this member while "
            "elected aggregator")
        self._c_gossip_err = r.counter(
            "fleet_gossip_errors_total",
            "failed peer heartbeat dials (lease expiry is the detector; "
            "this counts the evidence)")

    # -- heartbeat server --------------------------------------------------
    def start_server(self) -> str:
        fleet = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.rstrip("/") != HEALTH_PATH:
                    self.send_error(404)
                    return
                body = json.dumps(fleet.health_snapshot()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._httpd = FrameworkHTTPServer(
            (self.heartbeat_host, self.heartbeat_port), Handler)
        self.heartbeat_port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name=f"fleet-heartbeat-{self.member}",
                             daemon=True)
        t.start()
        return self.endpoint

    @property
    def endpoint(self) -> str:
        return f"http://{self.heartbeat_host}:{self.heartbeat_port}"

    # -- state reads -------------------------------------------------------
    def _consumers(self) -> list:
        if self.consumers_fn is None:
            return []
        try:
            return list(self.consumers_fn() or [])
        except Exception:  # noqa: BLE001 - a crash-recycling router may
            # briefly have no consumers; counted as gossip evidence
            self._c_gossip_err.inc(labels={"peer": "local"})
            return []

    def owned_partitions(self) -> list[int]:
        owned: set[int] = set()
        for c in self._consumers():
            a = getattr(c, "assignment", None)
            if callable(a):
                a = a()
            for _t, p in (a or []):
                owned.add(int(p))
        return sorted(owned)

    def group_epoch_view(self) -> int:
        return max((int(getattr(c, "epoch", 0)) for c in self._consumers()),
                   default=0)

    def _fingerprint(self) -> str | None:
        if self.fingerprint_fn is None:
            return None
        try:
            return self.fingerprint_fn()
        except Exception:  # noqa: BLE001 - an unknown fingerprint reads
            # as "warming up", never as stale; counted as evidence
            self._c_gossip_err.inc(labels={"peer": "fingerprint"})
            return None

    def _counters(self) -> dict[str, int]:
        if self.counters_fn is None:
            return {}
        try:
            return dict(self.counters_fn())
        except Exception:  # noqa: BLE001 - accounting snapshot is
            # best-effort on a mid-recycle router; counted
            self._c_gossip_err.inc(labels={"peer": "counters"})
            return {}

    def health_snapshot(self) -> dict[str, Any]:
        with self._mu:
            live = live_members(self._last_seen, self._clock(), self.ttl_s)
        return {
            "member": self.member,
            "incarnation": self.incarnation,
            "pid": os.getpid(),
            "epoch": self.group_epoch_view(),
            "partitions": self.owned_partitions(),
            "fingerprint": self._fingerprint(),
            "counters": self._counters(),
            "quarantined": self.parity_gate.quarantined,
            "live": live,
            "aggregator": elect_aggregator(live) == self.member,
            "admission_ceiling": (
                int(self.overload.budget.max_limit)
                if self.overload is not None else None),
        }

    # -- gossip loop -------------------------------------------------------
    def _client(self, peer: str):
        cl = self._peer_clients.get(peer)
        if cl is None:
            from ccfd_tpu.utils.httpclient import PooledHTTPClient

            cl = PooledHTTPClient(peer, default_port=80, pool_size=1,
                                  timeout_s=self._gossip_timeout_s,
                                  retries=0)
            self._peer_clients[peer] = cl
        return cl

    def _gossip_once(self, now: float) -> None:
        for peer in self.peers:
            if now < self._peer_next_dial.get(peer, 0.0):
                continue
            try:
                status, body = self._client(peer).request(
                    "GET", HEALTH_PATH)
            except ConnectionError:
                # dead/respawning peer: jittered exponential backoff on
                # the redial (runtime/breaker.backoff_s) — detection
                # itself rides the lease expiry, not this dial
                attempt = self._peer_attempts.get(peer, 0)
                self._peer_attempts[peer] = attempt + 1
                self._peer_next_dial[peer] = now + backoff_s(
                    attempt, base_s=0.2, cap_s=self.ttl_s, rng=self._rng)
                self._c_gossip_err.inc(labels={"peer": peer})
                continue
            self._peer_attempts[peer] = 0
            self._peer_next_dial[peer] = 0.0
            if status != 200 or not isinstance(body, dict):
                self._c_gossip_err.inc(labels={"peer": peer})
                continue
            name = str(body.get("member", peer))
            with self._mu:
                self._last_seen[name] = now
                self._fingerprints[name] = body.get("fingerprint")
                self._incarnations[name] = str(body.get("incarnation", ""))
                self._peer_health[name] = body

    def tick(self) -> dict[str, Any]:
        """One gossip round: dial peers, refresh the lease table, run the
        fleet actuators (admission rescale, parity quarantine, aggregator
        duty), publish the gauges. Returns the tick's fleet view (the
        drills assert on it)."""
        now = self._clock()
        self._gossip_once(now)
        with self._mu:
            self._last_seen[self.member] = now
            self._fingerprints[self.member] = self._fingerprint()
            self._incarnations.setdefault(self.member, self.incarnation)
            live = live_members(self._last_seen, now, self.ttl_s)
            fps = {m: self._fingerprints.get(m) for m in live}
            incarnations = dict(self._incarnations)
            prev_live = set(self._prev_live)
            self._prev_live = set(live)
        epoch = self.group_epoch_view()
        owned = set(self.owned_partitions())
        parity = check_fingerprint_parity(fps)
        aggregator = elect_aggregator(live)

        # actuator 1: fleet admission — equal share of the global ceiling
        share = None
        if self.overload is not None and self._global_ceiling > 0:
            share = admission_share(self._global_ceiling, len(live))
            self.overload.budget.rescale_ceiling(share)
            self._g_share.set(float(share))

        # actuator 2: champion parity — stale member self-quarantines
        if self.member in parity["stale"]:
            self.parity_gate.quarantine(
                f"champion fingerprint diverges from fleet majority "
                f"{str(parity['majority'])[:12]}")
        else:
            self.parity_gate.release()

        # actuator 3: aggregator duty — one bundle per killed incarnation
        dead = sorted(prev_live - set(live) - {self.member})
        if dead and aggregator == self.member and self.recorder is not None:
            for m in dead:
                key = (m, incarnations.get(m, ""))
                if key in self._reported_kills:
                    continue
                self._reported_kills.add(key)
                try:
                    self.recorder.incident({
                        "type": "fleet_member_kill",
                        "member": m,
                        "incarnation": key[1],
                        "survivors": live,
                        "epoch": epoch,
                    })
                    self._c_kills.inc()
                except Exception:  # noqa: BLE001 - evidence, never a
                    # crash; the kill stays visible via ccfd_fleet_members
                    self._c_gossip_err.inc(labels={"peer": "incident"})

        self._g_members.set(float(len(live)))
        self._g_epoch.set(float(epoch))
        self._g_parity.set(1.0 if parity["parity"] else 0.0)
        self._g_aggregator.set(1.0 if aggregator == self.member else 0.0)
        for p in owned:
            self._g_owner.set(1.0, labels={"partition": str(p)})
        for p in self._prev_owned - owned:
            self._g_owner.set(0.0, labels={"partition": str(p)})
        self._prev_owned = owned
        return {
            "live": live,
            "epoch": epoch,
            "partitions": sorted(owned),
            "parity": parity,
            "aggregator": aggregator,
            "admission_ceiling": share,
            "dead": dead,
        }

    # -- supervised-service surface ---------------------------------------
    def run(self, interval_s: float = 0.5) -> None:
        while not self._stop.wait(interval_s):
            self.tick()

    def stop(self) -> None:
        self._stop.set()

    def reset(self) -> None:
        self._stop.clear()

    def close(self) -> None:
        self.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for cl in self._peer_clients.values():
            try:
                cl.close()
            except Exception:  # noqa: BLE001 - teardown must not raise;
                # nothing to account, the process is exiting
                log.debug("peer client close failed", exc_info=True)
        self._peer_clients.clear()
