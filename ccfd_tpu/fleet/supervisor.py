"""FleetSupervisor: spawn / kill / fence / respawn operator member processes.

The fleet's failure model is a HARD host kill (SIGKILL — no atexit, no
socket close, no offset commit), so members must be real OS processes:
``python -m ccfd_tpu fleet member --spec <json>`` each brings up a full
``platform.operator`` Platform from a CR-shaped spec file written here.
The supervisor is the drill/ops actor around them:

* **spawn** — write the member's CR spec under ``state_dir`` and exec the
  member entrypoint (stdout/stderr captured to per-member log files);
* **kill** — SIGKILL the process, then **fence** the dead member's bus
  consumers (``POST /groups/<g>/fence`` with an idle threshold so the
  SURVIVORS' actively-polling consumers are spared): the group rebalance
  bumps the epoch, survivors re-adopt the dead member's partitions, and
  any in-flight commit from the corpse is refused by the epoch fence;
* **respawn** — start a fresh incarnation under jittered backoff
  (runtime/breaker.backoff_s) and wait for its heartbeat endpoint.

Nothing here runs inside a member: the supervisor is bus-client + process
babysitter only, so killing IT loses no fleet state (membership is
gossip, ownership is the bus's consumer group).
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Mapping

from ccfd_tpu.fleet.ledger import LEDGER_TOPIC
from ccfd_tpu.fleet.member import HEALTH_PATH
from ccfd_tpu.runtime.breaker import backoff_s
from ccfd_tpu.runtime.durability import write_json_interchange

log = logging.getLogger(__name__)

ROUTER_GROUP = "router"


def _free_port(host: str = "127.0.0.1") -> int:
    """Bind-probe a free TCP port. Racy by nature (the port is free only
    until someone binds it) — good enough for drills on a quiet loopback;
    production CRs pin real ports."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def build_member_cr(
    member: str,
    bus_url: str,
    heartbeat_port: int,
    peers: list[str],
    state_dir: str,
    *,
    ttl_s: float = 3.0,
    gossip_interval_s: float = 0.25,
    global_max_inflight: int = 0,
    ledger_topic: str = LEDGER_TOPIC,
    monitoring_port: int = 0,
    overrides: Mapping[str, Any] | None = None,
) -> dict[str, Any]:
    """CR-shaped spec for one fleet member: a routing-only operator slice
    (scorer + engine + router + overload + incident + fleet) over the
    SHARED networked bus. Heavy/irrelevant planes are off — members must
    come up in seconds, and planes that write shared files (audit dir,
    lifecycle state) would collide across processes. ``overrides`` deep-
    merges per-component blocks on top (drills tighten knobs with it)."""
    spec: dict[str, Any] = {
        "bus": {"url": bus_url},
        "fleet": {
            "enabled": True,
            "member": member,
            "heartbeat_port": int(heartbeat_port),
            "peers": list(peers),
            "ttl_s": float(ttl_s),
            "gossip_interval_s": float(gossip_interval_s),
            "global_max_inflight": int(global_max_inflight),
            "ledger_topic": ledger_topic,
        },
        # commit-after-route + the ledger tap need the single-Router shape
        # (one tx consumer whose poll epoch stamps the batch)
        "router": {"workers": 1},
        "monitoring": {"port": int(monitoring_port)},
        "incident": {"dir": os.path.join(state_dir, f"incidents-{member}")},
        # identical fingerprints across members come from the scorer's
        # deterministic seed-0 init; anything that retrains or restores
        # per-member state would fork the champion, so it stays off
        "retrain": False,
        "lifecycle": False,
        "analytics": False,
        "notify": False,
        "engine": {"enabled": True},
        "health": False,
        "audit": False,
        "heal": False,
        "slo": False,
        "device": False,
        "tracing": False,
        "mesh": False,
        "durability": False,
    }
    for name, block in (overrides or {}).items():
        if isinstance(block, Mapping) and isinstance(spec.get(name), dict):
            spec[name].update(block)
        else:
            spec[name] = block
    return {"spec": spec}


class FleetSupervisor:
    """Babysits N member processes over one shared bus (module docstring).

    ``registry`` (optional metrics.prom.Registry) lands the supervisor's
    own counters: ``fleet_spawns_total{member}``,
    ``fleet_kills_total{member}``, ``fleet_fences_total``.
    """

    def __init__(
        self,
        bus_url: str,
        state_dir: str,
        group: str = ROUTER_GROUP,
        registry: Any = None,
        python: str | None = None,
        env: Mapping[str, str] | None = None,
    ):
        self.bus_url = bus_url.rstrip("/")
        self.state_dir = state_dir
        self.group = group
        self.python = python or sys.executable
        self.env = dict(env) if env is not None else None
        os.makedirs(state_dir, exist_ok=True)
        self.members: dict[str, dict[str, Any]] = {}
        self._clients: dict[str, Any] = {}
        self._c_spawns = self._c_kills = self._c_fences = None
        if registry is not None:
            self._c_spawns = registry.counter(
                "fleet_spawns_total", "member processes started")
            self._c_kills = registry.counter(
                "fleet_kills_total", "member processes hard-killed")
            self._c_fences = registry.counter(
                "fleet_fences_total",
                "bus consumer-group fences issued after a kill")

    # -- membership --------------------------------------------------------
    def add_member(self, name: str, cr: Mapping[str, Any]) -> str:
        """Register a member and persist its CR spec file; returns the
        spec path. The heartbeat endpoint is read back out of the CR so
        callers build it once (build_member_cr)."""
        spec = cr.get("spec", cr)
        port = int(spec.get("fleet", {}).get("heartbeat_port", 0))
        if port <= 0:
            raise ValueError(f"member {name}: CR must pin a heartbeat_port")
        path = os.path.join(self.state_dir, f"member-{name}.json")
        write_json_interchange(path, cr, artifact="fleet_member_cr",
                               indent=2)
        self.members[name] = {
            "spec_path": path,
            "endpoint": f"http://127.0.0.1:{port}",
            "proc": None,
            "spawns": 0,
        }
        return path

    def spawn(self, name: str) -> int:
        """Start (or restart) the member process; returns its pid."""
        m = self.members[name]
        if m["proc"] is not None and m["proc"].poll() is None:
            return m["proc"].pid
        logf = open(  # noqa: SIM115 - handed to the child, closed on kill
            os.path.join(self.state_dir, f"member-{name}.log"), "ab")
        m["log"] = logf
        m["proc"] = subprocess.Popen(
            [self.python, "-m", "ccfd_tpu", "fleet", "member",
             "--spec", m["spec_path"]],
            stdout=logf, stderr=subprocess.STDOUT,
            env=self.env,
        )
        m["spawns"] += 1
        if self._c_spawns is not None:
            self._c_spawns.inc(labels={"member": name})
        log.info("fleet member %s spawned pid=%d", name, m["proc"].pid)
        return m["proc"].pid

    def kill(self, name: str, fence_idle_s: float = 0.5,
             settle_s: float = 1.0) -> None:
        """HARD kill: SIGKILL the member, give the survivors ``settle_s``
        of active polling, then fence the group — the bus closes consumers
        idle longer than ``fence_idle_s`` (the corpse's), rebalances, and
        bumps the epoch so the dead member's partitions re-home with its
        in-flight commits refused."""
        m = self.members[name]
        proc = m["proc"]
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        if m.get("log") is not None:
            m["log"].close()
            m["log"] = None
        if self._c_kills is not None:
            self._c_kills.inc(labels={"member": name})
        time.sleep(settle_s)
        self.fence(idle_s=fence_idle_s)

    def fence(self, idle_s: float = 0.5) -> dict[str, Any]:
        from ccfd_tpu.bus.client import RemoteBroker

        broker = RemoteBroker(self.bus_url)
        try:
            out = broker.fence_group(self.group, idle_s=idle_s)
        finally:
            broker.close()
        if self._c_fences is not None:
            self._c_fences.inc()
        log.info("fenced group %s: %s", self.group, out)
        return out

    def respawn(self, name: str, timeout_s: float = 30.0) -> int:
        """Fresh incarnation under jittered backoff until its heartbeat
        answers; raises TimeoutError if it never does."""
        deadline = time.monotonic() + timeout_s
        attempt = 0
        pid = self.spawn(name)
        while time.monotonic() < deadline:
            if self.health(name) is not None:
                return pid
            if self.members[name]["proc"].poll() is not None:
                # the incarnation died during bring-up: try another
                pid = self.spawn(name)
            time.sleep(backoff_s(attempt, base_s=0.2, cap_s=2.0))
            attempt += 1
        raise TimeoutError(f"member {name} did not become ready "
                           f"in {timeout_s}s")

    # -- health ------------------------------------------------------------
    def _client(self, name: str):
        cl = self._clients.get(name)
        if cl is None:
            from ccfd_tpu.utils.httpclient import PooledHTTPClient

            cl = PooledHTTPClient(self.members[name]["endpoint"],
                                  default_port=80, pool_size=1,
                                  timeout_s=2.0, retries=0)
            self._clients[name] = cl
        return cl

    def health(self, name: str) -> dict[str, Any] | None:
        try:
            status, body = self._client(name).request("GET", HEALTH_PATH)
        except (ConnectionError, OSError):
            return None
        return body if status == 200 and isinstance(body, dict) else None

    def wait_ready(self, names: list[str] | None = None,
                   timeout_s: float = 60.0) -> None:
        deadline = time.monotonic() + timeout_s
        pending = list(names if names is not None else self.members)
        while pending and time.monotonic() < deadline:
            pending = [n for n in pending if self.health(n) is None]
            if pending:
                time.sleep(0.2)
        if pending:
            raise TimeoutError(f"members not ready in {timeout_s}s: "
                               f"{pending}")

    def ownership(self) -> dict[str, list[int]]:
        """{member: owned partitions} over members that answer health —
        check with protocol.check_disjoint_ownership."""
        out: dict[str, list[int]] = {}
        for name in self.members:
            h = self.health(name)
            if h is not None:
                out[name] = [int(p) for p in h.get("partitions", [])]
        return out

    def status(self) -> dict[str, Any]:
        return {
            name: {
                "pid": (m["proc"].pid if m["proc"] is not None else None),
                "alive": (m["proc"] is not None
                          and m["proc"].poll() is None),
                "spawns": m["spawns"],
                "endpoint": m["endpoint"],
                "health": self.health(name),
            }
            for name, m in self.members.items()
        }

    # -- teardown ----------------------------------------------------------
    def stop_all(self, grace_s: float = 10.0) -> None:
        for name, m in self.members.items():
            proc = m["proc"]
            if proc is not None and proc.poll() is None:
                proc.terminate()  # SIGTERM -> the member's graceful path
        deadline = time.monotonic() + grace_s
        for name, m in self.members.items():
            proc = m["proc"]
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                log.warning("member %s ignored SIGTERM; killing", name)
                proc.kill()
                proc.wait(timeout=10)
            if m.get("log") is not None:
                m["log"].close()
                m["log"] = None
        for cl in self._clients.values():
            try:
                cl.close()
            except Exception:  # noqa: BLE001 - teardown must not raise;
                # nothing to account, the supervisor is exiting
                log.debug("health client close failed", exc_info=True)
        self._clients.clear()
