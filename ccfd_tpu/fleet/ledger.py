"""FleetLedgerTap: per-transaction route dispositions onto a bus topic.

The fleet's conservation proof ("no drop, no double-route" across a hard
member kill) cannot stand on scraped counters alone: a SIGKILLed member
takes its counters with it. What survives the kill is the BUS — the one
shared component — so each member publishes a compact ledger entry per
routed transaction to a fleet topic (``fleet.ledger``), stamped with the
member id and the consumer-group epoch the batch was polled under. The
drill (tools/fleet_drill.py) then replays the ledger and checks the law
with :func:`ccfd_tpu.fleet.protocol.check_ledger_conservation`:

* every produced tx has >= 1 disposition (no drop — a member killed
  mid-batch leaves its offsets uncommitted, so the batch redelivers);
* no tx is disposed twice under ONE epoch (no double-route — the bus's
  epoch fence refuses the dead member's in-flight commit);
* cross-epoch duplicates are counted as at-least-once redeliveries.

The tap sits in the router's audit seam (the operator installs it as the
router's ``audit`` when the fleet component is up): ``record_batch`` is
called at the route seam with exactly the rows that started a process,
BEFORE the batch's offsets commit — so a kill between route and commit
yields a redelivery (counted), never a gap. It forwards to an inner
:class:`~ccfd_tpu.observability.audit.AuditLog` when the provenance
plane is armed, so fleet mode stacks on top of — never replaces — the
per-decision audit trail.

Publishing is best-effort like every observability writer: a bus edge
failure counts (``fleet_ledger_publish_errors_total``) and routing never
stalls. The entries it would have published are then missing from the
ledger — the drill reads that as a drop, which is the honest verdict
when the accounting evidence itself was lost.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Mapping

log = logging.getLogger(__name__)

LEDGER_TOPIC = "fleet.ledger"


class FleetLedgerTap:
    """Audit-shaped tap publishing one ledger entry per routed row.

    Duck-types the router's audit surface (``record_batch``); everything
    else the operator wires on the inner AuditLog directly. ``epoch_fn``
    is set by the operator AFTER the router exists (it reads the tx
    consumer's poll epoch); until then entries carry ``epoch=None``,
    which the conservation checker treats as one more distinct epoch —
    conservative: it can only turn a real same-epoch double-route into
    a reported one, never hide one.
    """

    def __init__(
        self,
        broker: Any,
        member: str,
        topic: str = LEDGER_TOPIC,
        inner: Any = None,
        epoch_fn: Callable[[], int | None] | None = None,
        registry: Any = None,
    ):
        self.broker = broker
        self.member = str(member)
        self.topic = topic
        self.inner = inner
        self.epoch_fn = epoch_fn
        self._c_entries = self._c_err = None
        if registry is not None:
            self._c_entries = registry.counter(
                "fleet_ledger_entries_total",
                "route dispositions published to the fleet ledger topic",
            )
            self._c_err = registry.counter(
                "fleet_ledger_publish_errors_total",
                "ledger batches lost to bus-edge failures (best-effort "
                "writer: routing never stalls on the ledger)",
            )

    def record_batch(
        self,
        rows: list[dict],
        *,
        tier: str = "device",
        cause: str | None = None,
        events: tuple | list = (),
        worker: int | None = None,
        trace_id: str | None = None,
        threshold: float | None = None,
    ) -> None:
        if self.inner is not None:
            # the provenance plane's own error handling applies inside
            self.inner.record_batch(
                rows, tier=tier, cause=cause, events=events, worker=worker,
                trace_id=trace_id, threshold=threshold,
            )
        if not rows:
            return
        epoch = None
        if self.epoch_fn is not None:
            try:
                epoch = self.epoch_fn()
            except Exception:  # noqa: BLE001 - epoch is advisory; None is
                # the conservative stamp (see class docstring)
                if self._c_err is not None:
                    self._c_err.inc(labels={"stage": "epoch"})
        entries = [
            {"tx": r.get("tx"), "uid": r.get("uid"), "tier": tier}
            for r in rows
        ]
        try:
            self.broker.produce(
                self.topic,
                {"member": self.member, "epoch": epoch, "entries": entries},
                key=self.member,
            )
            if self._c_entries is not None:
                self._c_entries.inc(len(entries))
        except Exception:  # noqa: BLE001 - best-effort writer (docstring):
            # the loss is counted and the drill reads the gap as a drop
            if self._c_err is not None:
                self._c_err.inc(labels={"stage": "produce"})
            log.warning("fleet ledger publish failed (%d entries)",
                        len(entries), exc_info=True)


def flatten_ledger(records: list[Any]) -> list[dict[str, Any]]:
    """Explode polled ledger bus records into per-tx entries for
    :func:`ccfd_tpu.fleet.protocol.check_ledger_conservation` — each
    entry re-carries its batch's ``member``/``epoch`` stamps."""
    out: list[dict[str, Any]] = []
    for rec in records:
        v = rec.value if hasattr(rec, "value") else rec
        if not isinstance(v, Mapping):
            continue
        member, epoch = v.get("member"), v.get("epoch")
        for e in v.get("entries", ()):
            out.append({"tx": e.get("tx"), "uid": e.get("uid"),
                        "tier": e.get("tier"), "member": member,
                        "epoch": epoch})
    return out
