"""Fleet plane: N operator processes over ONE shared bus (ISSUE 16).

Horizontal scaling for the serving pipeline — the reference system's k8s
replicas-over-Kafka story (SURVEY.md §2), built from parts this repo
already has: the networked bus (bus/server.py) carries partition
ownership via consumer groups with an epoch fence, each member is a full
``platform.operator`` process, and the fleet layer adds membership
(heartbeat gossip), fleet-wide admission rescale, champion-parity
quarantine, and a supervisor that kills/fences/respawns members.

    protocol.py    pure membership/assignment/parity functions (no jax,
                   CI-gated by tier-1 tests)
    member.py      FleetMember: heartbeat server + gossip loop + gauges
    supervisor.py  FleetSupervisor: spawn/kill/fence/respawn member procs
    ledger.py      FleetLedgerTap: per-tx route dispositions to a bus
                   topic — the durable fleet accounting ledger
"""

from ccfd_tpu.fleet.protocol import (  # noqa: F401
    check_disjoint_ownership,
    check_fingerprint_parity,
    elect_aggregator,
    live_members,
    plan_partition_assignment,
)
