"""ccfd-lint: the repo's review findings as machine-checked invariants.

Fourteen PRs of review hardening kept re-finding the same defect classes
by hand: persistent writers bypassing the durability seam (PR 13's whole
motivation), ``time.time()`` pairs used as durations (the PR 2 NTP-step
bug), silent drops that never touched a counter (the "no silent caps"
invariant), breaker paths recording zero or two outcomes, host syncs on
the device hot path, and lock inversions that only live drills caught
(PR 8's eviction-stamp race, PR 12's publish-gate leak). The repo's
conventions are structured enough to check mechanically (PRETZEL's
white-box thesis applied to correctness tooling), so this package turns
each class into a named rule over Python ``ast``:

- :mod:`ccfd_tpu.analysis.core` — rule registry, per-line suppression
  pragmas (``# ccfd-lint: disable=<rule> -- why``), a checked-in baseline
  for grandfathered findings, human + strict-JSON reports.
- :mod:`ccfd_tpu.analysis.rules` — the seven invariants (see each rule's
  ``invariant`` string for the PR that motivated it).
- :mod:`ccfd_tpu.analysis.lockcheck` — the runtime half of the lock-order
  rule: ``CCFD_LOCKCHECK=1`` wraps ``threading.Lock``/``RLock`` so the
  per-thread acquisition-order graph is recorded live and a cycle fails
  the process instead of deadlocking a drill three PRs later.

Run via ``ccfd_tpu lint`` (gated in ``tools/verify_tier1.sh --lint``).
This package must stay importable without jax: the lint gate and the
lock sanitizer both run in contexts (CI shells, conftest bootstrap)
where initializing an accelerator backend is wrong.
"""

from ccfd_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintReport,
    Rule,
    lint_sources,
    load_baseline,
    run_lint,
)
