"""ccfd-lint engine: rule registry, pragmas, baseline, reports.

Deliberately dependency-free (stdlib ``ast`` only): the lint gate runs
before anything else in CI and must not pay — or wedge on — accelerator
imports. Rules are small classes registered by name; each one encodes a
single named invariant from the change history (see rules.py).

Suppression contract (mirrors the noqa idiom already in the tree):

    x = risky()  # ccfd-lint: disable=<rule>[,<rule>] -- justification

applies to that physical line; a pragma comment alone on a line applies
to the next line (for calls whose expression spans lines, put the pragma
on the line the call STARTS on). ``disable-file=<rule>`` anywhere in the
file suppresses the rule for the whole file. The ``-- justification``
text is part of the contract: a suppression without one is itself a
finding (``bare-pragma``), so every grandfathered site explains itself
in place.

The baseline file (``tools/lint_baseline.json``) grandfathers findings
by content-stable key (rule + path + normalized source line) so line
drift doesn't churn it. The merge bar for this repo is an EMPTY
baseline: fixes and justified inline pragmas are the steady state; the
baseline exists for incremental adoption and for the round-trip test.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Callable, Iterable, Mapping

LINT_SCHEMA_VERSION = 1

_PRAGMA_RE = re.compile(
    r"#\s*ccfd-lint:\s*(disable(?:-file)?)=([\w,\-]+)(?:\s+--\s*(\S.*))?"
)
_HOT_PATH_RE = re.compile(r"#\s*ccfd-lint:\s*hot-path\b")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int  # 1-based
    col: int  # 0-based
    message: str
    snippet: str = ""

    def key(self) -> str:
        """Content-stable baseline identity: rule + path + the flagged
        source line with whitespace normalized (line NUMBERS drift with
        every edit above the site; the line's content does not)."""
        norm = " ".join(self.snippet.split())
        h = hashlib.sha256(norm.encode()).hexdigest()[:16]
        return f"{self.rule}:{self.path}:{h}"

    def to_dict(self) -> dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
            "key": self.key(),
        }


class FileContext:
    """Parsed view of one source file handed to every rule: AST, raw
    lines, pragma maps. Built from (path, source) so tests lint virtual
    snippets without touching disk."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of rule names disabled on that line
        self.disabled: dict[int, set[str]] = {}
        self.disabled_file: set[str] = set()
        # lines carrying a pragma with NO justification text
        self.bare_pragma_lines: list[int] = []
        self.hot_path_lines: set[int] = set()
        self._scan_pragmas()

    def _scan_pragmas(self) -> None:
        # tokenize, not a raw line scan: pragma-shaped text inside a
        # STRING literal (help text, a docstring documenting the syntax)
        # must never act as a live suppression
        import io
        import tokenize

        comments: list[tuple[int, int, str]] = []
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.start[1], tok.string))
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            return  # token-broken source; ast.parse already vets files
        for i, col, text in comments:
            if _HOT_PATH_RE.search(text):
                self.hot_path_lines.add(i)
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            kind, rules_csv, justification = m.groups()
            rules = {r.strip() for r in rules_csv.split(",") if r.strip()}
            if not justification:
                self.bare_pragma_lines.append(i)
            if kind == "disable-file":
                self.disabled_file |= rules
                continue
            # a pragma applies to its own line, and — when the line is
            # pure comment — to the following line as well
            self.disabled.setdefault(i, set()).update(rules)
            if i - 1 < len(self.lines) and not self.lines[i - 1][:col].strip():
                self.disabled.setdefault(i + 1, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.disabled_file:
            return True
        return rule in self.disabled.get(line, set())

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message, snippet=self.snippet_at(line))


class Rule:
    """Base rule. ``scope`` is ``"file"`` (checked per FileContext) or
    ``"project"`` (handed every FileContext at once — the lock-order
    graph needs the whole tree)."""

    name = ""
    invariant = ""  # one-line statement of the invariant this encodes
    motivated_by = ""  # the PR / review finding that motivated it
    scope = "file"

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def check_project(
        self, ctxs: list[FileContext]
    ) -> Iterable[Finding]:  # pragma: no cover - project rules override
        raise NotImplementedError


_REGISTRY: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator: add a rule to the global registry by name."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def registered_rules() -> dict[str, type[Rule]]:
    # rules.py registers on import; import lazily so core stays cycle-free
    from ccfd_tpu.analysis import rules as _rules  # noqa: F401

    return dict(_REGISTRY)


# -- baseline ----------------------------------------------------------------


def load_baseline(path: str | None) -> dict[str, dict[str, Any]]:
    """Baseline file -> {finding key: entry}. Missing file reads as an
    empty baseline; a malformed one raises (a silently-ignored baseline
    would un-grandfather everything and fail the gate confusingly)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != LINT_SCHEMA_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {doc.get('version')!r}")
    out: dict[str, dict[str, Any]] = {}
    for entry in doc.get("findings", []):
        key = entry.get("key") if isinstance(entry, dict) else None
        if not key:
            # ValueError, not KeyError: the CLI's malformed-baseline
            # handler prints a one-line diagnosis and exits 2
            raise ValueError(
                f"baseline {path}: entry without a 'key' field: {entry!r}")
        out[key] = entry
    return out


def write_baseline(path: str, findings: list[Finding]) -> dict[str, Any]:
    doc = {
        "version": LINT_SCHEMA_VERSION,
        "comment": (
            "grandfathered ccfd-lint findings; every entry needs a "
            "justification or a fix — the steady state is an empty list"
        ),
        "findings": [
            {**f.to_dict(), "justification": ""} for f in findings
        ],
    }
    with open(path, "w") as f:  # ccfd-lint: disable=durability-seam -- dev-tool output, reviewed and checked in like source, not a runtime artifact
        f.write(json.dumps(doc, indent=1, sort_keys=True))
        f.write("\n")
    return doc


# -- runner ------------------------------------------------------------------


@dataclasses.dataclass
class LintReport:
    findings: list[Finding]  # active (unsuppressed, unbaselined)
    suppressed: list[Finding]
    baselined: list[Finding]
    files_scanned: int
    parse_errors: list[str]

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.parse_errors) else 0

    def to_json(self) -> dict[str, Any]:
        """Strict-JSON report (schema asserted by tests/test_lint.py)."""
        rules = registered_rules()
        return {
            "version": LINT_SCHEMA_VERSION,
            "tool": "ccfd-lint",
            "files_scanned": self.files_scanned,
            "rules": [
                {
                    "name": name,
                    "invariant": cls.invariant,
                    "motivated_by": cls.motivated_by,
                }
                for name, cls in sorted(rules.items())
            ],
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "baselined": [f.to_dict() for f in self.baselined],
            "parse_errors": list(self.parse_errors),
            "counts": {
                "active": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
            },
            "exit": self.exit_code,
        }

    def human_lines(self) -> list[str]:
        out = [
            f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.message}"
            for f in self.findings
        ]
        out.extend(f"parse error: {e}" for e in self.parse_errors)
        tail = (
            f"ccfd-lint: {len(self.findings)} finding(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined, "
            f"{self.files_scanned} file(s)"
        )
        out.append(tail)
        return out


def iter_py_files(root: str, paths: Iterable[str] | None = None) -> list[str]:
    """Source files to lint, repo-relative. Default scope is the
    ``ccfd_tpu`` package — tools/ and tests/ have different conventions
    (they write interchange JSON everywhere, by design)."""
    rels: list[str] = []
    targets = list(paths) if paths else ["ccfd_tpu"]
    for target in targets:
        full = os.path.join(root, target)
        found: list[str] = []
        if os.path.isfile(full) and full.endswith(".py"):
            found.append(os.path.relpath(full, root))
        else:
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        found.append(
                            os.path.relpath(os.path.join(dirpath, fn), root))
        if not found:
            # a typo'd target must FAIL the gate, not scan zero files and
            # report a clean tree — the silent-cap failure mode this tool
            # exists to refuse
            raise ValueError(
                f"lint target {target!r} matched no python files under "
                f"{root}")
        rels.extend(found)
    return sorted(set(r.replace(os.sep, "/") for r in rels))


def _check_bare_pragmas(ctx: FileContext) -> list[Finding]:
    """A suppression without a justification is itself a finding: the
    pragma contract is that every grandfathered site explains itself."""
    out = []
    for line in ctx.bare_pragma_lines:
        out.append(Finding(
            rule="bare-pragma", path=ctx.path, line=line, col=0,
            message=("suppression pragma without a justification; write "
                     "`# ccfd-lint: disable=<rule> -- <why>`"),
            snippet=ctx.snippet_at(line)))
    return out


def lint_sources(
    sources: Mapping[str, str],
    rule_names: Iterable[str] | None = None,
    baseline: Mapping[str, Any] | None = None,
) -> LintReport:
    """Lint in-memory {path: source} — the engine under both the CLI and
    the unit-test fixtures."""
    rules = registered_rules()
    if rule_names is not None:
        unknown = set(rule_names) - set(rules)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        rules = {n: rules[n] for n in rule_names}
    baseline = baseline or {}

    ctxs: list[FileContext] = []
    parse_errors: list[str] = []
    for path, source in sorted(sources.items()):
        try:
            ctxs.append(FileContext(path, source))
        except SyntaxError as e:
            parse_errors.append(f"{path}: {e.msg} (line {e.lineno})")

    raw: list[Finding] = []
    for name, cls in sorted(rules.items()):
        rule = cls()
        if rule.scope == "project":
            raw.extend(rule.check_project(ctxs))
        else:
            for ctx in ctxs:
                raw.extend(rule.check(ctx))
    for ctx in ctxs:
        raw.extend(_check_bare_pragmas(ctx))

    by_path = {c.path: c for c in ctxs}
    active: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        ctx = by_path.get(f.path)
        if ctx is not None and ctx.suppressed(f.rule, f.line):
            suppressed.append(f)
        elif f.key() in baseline:
            baselined.append(f)
        else:
            active.append(f)
    return LintReport(findings=active, suppressed=suppressed,
                      baselined=baselined, files_scanned=len(ctxs),
                      parse_errors=parse_errors)


def run_lint(
    root: str,
    paths: Iterable[str] | None = None,
    baseline_path: str | None = None,
    rule_names: Iterable[str] | None = None,
    read: Callable[[str], str] | None = None,
) -> LintReport:
    """Lint files under ``root`` (repo top). ``read`` is injectable for
    tests; defaults to the filesystem."""
    files = iter_py_files(root, paths)
    if read is None:
        def read(rel: str) -> str:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                return f.read()
    sources = {rel: read(rel) for rel in files}
    return lint_sources(sources, rule_names=rule_names,
                        baseline=load_baseline(baseline_path))
