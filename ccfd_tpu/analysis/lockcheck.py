"""Runtime lock-order sanitizer: the dynamic half of the lock-order rule.

The static rule (analysis/rules.py) sees lexically nested ``with`` sites;
it cannot see an inversion that happens through a method call made while
holding a lock — exactly the shape of PR 8's eviction-stamp race and
PR 12's publish-gate leak, which only live kill-storm drills caught.
This module is lockdep-lite for those: with ``CCFD_LOCKCHECK=1``,
:func:`install` replaces ``threading.Lock``/``threading.RLock`` with a
factory that wraps every lock constructed FROM THEN ON in a checked
proxy. Each acquisition records, per thread, the edge (every lock
currently held) -> (lock being acquired) into one process-global
acquisition-order graph; the first edge that closes a cycle is a proven
ordering inversion — two interleavings away from a deadlock — and fails
the process right there (:class:`LockOrderError`), instead of hanging a
soak three PRs later.

Design notes, hard-won:

- **Per-instance nodes.** Aggregating by construction site would flag
  two shard locks of the same stripe acquired in address order as a
  self-cycle. Per-instance edges only ever flag inversions that two real
  lock objects actually exhibited. Node ids are monotonic tokens, not
  ``id()`` — CPython recycles addresses after GC.
- **Reentrancy.** Re-acquiring an RLock already held by this thread adds
  no edge (it cannot deadlock against itself).
- **Condition compatibility.** ``threading.Condition`` reaches the
  protocol methods (``_release_save``/``_acquire_restore``/``_is_owned``)
  through ``__getattr__`` delegation to the real lock, so ``wait()``
  bypasses the tracker symmetrically on release and re-acquire: the
  bookkeeping still matches the logical held-state on both sides of the
  wait.
- **Hot-path cost.** The common case (acquire with nothing held, or an
  edge already known) touches only a thread-local list and a frozenset
  lookup; the global mutex is taken only for NEW edges, which are O(lock
  pairs) per process lifetime.

Armed by tests/conftest.py and tools/chaos_soak.py when CCFD_LOCKCHECK=1;
``tools/verify_tier1.sh --lint-smoke`` is the CI gate that proves the
healthy tree stays silent under kill-storms while a deliberate inversion
(tests/test_lint.py) still trips it.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import traceback
from typing import Any

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class LockOrderError(RuntimeError):
    """Two locks were acquired in opposite orders by different paths."""


def raw_lock():
    """An UNchecked lock, regardless of install state — for the
    sanitizer's own internals and for tests that build deliberate
    inversions against a private graph without tripping the global one."""
    return _REAL_LOCK()


def raw_rlock():
    return _REAL_RLOCK()


class LockGraph:
    """One acquisition-order graph + its violation log. The module holds
    a global instance for :func:`install`; tests construct their own and
    wrap locks explicitly via :meth:`wrap`."""

    def __init__(self, raise_on_cycle: bool = True):
        self.raise_on_cycle = raise_on_cycle
        self._mu = _REAL_LOCK()
        self._tokens = itertools.count(1)
        self._labels: dict[int, str] = {}
        self._adj: dict[int, set[int]] = {}
        # frozen read-mostly view for the lock-free fast path: rebuilt on
        # every new edge (rare), read on every nested acquire (hot)
        self._known_edges: frozenset[tuple[int, int]] = frozenset()
        self._tls = threading.local()
        self.violations: list[dict[str, Any]] = []

    # -- wrapping ----------------------------------------------------------
    def new_token(self, label: str) -> int:
        with self._mu:
            tok = next(self._tokens)
            self._labels[tok] = label
        return tok

    def wrap(self, lock: Any, label: str) -> "_CheckedLock":
        return _CheckedLock(lock, self, self.new_token(label))

    # -- per-thread held stack ---------------------------------------------
    def _held(self) -> list[int]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def note_acquired(self, token: int) -> None:
        held = self._held()
        if token in held:  # RLock reentry: no edge, no deadlock potential
            held.append(token)
            return
        new_edges = [
            (h, token) for h in dict.fromkeys(held)
            if (h, token) not in self._known_edges
        ]
        held.append(token)
        if not new_edges:
            return
        with self._mu:
            for a, b in new_edges:
                self._adj.setdefault(a, set()).add(b)
            cycle = None
            bad_edge = None
            for a, b in new_edges:
                cycle = self._cycle_through(b, a)
                if cycle:
                    cycle = cycle + [b]
                    bad_edge = (a, b)
                    break
            if self.raise_on_cycle and bad_edge is not None:
                # un-commit the cycle-closing edge: detection must not be
                # one-shot — a REPEAT of the same inversion (e.g. after a
                # broad except swallowed the first LockOrderError) has to
                # re-detect and re-raise, not ride the known-edge fast
                # path straight into the real deadlock
                self._adj[bad_edge[0]].discard(bad_edge[1])
            self._known_edges = frozenset(
                (a, b) for a, nbrs in self._adj.items() for b in nbrs)
            if cycle is None:
                return
            names = [self._labels.get(t, f"lock#{t}") for t in cycle]
            violation = {
                "cycle": names,
                "thread": threading.current_thread().name,
                "stack": "".join(traceback.format_stack(limit=12)[:-2]),
            }
            self.violations.append(violation)
        print(
            "[ccfd-lockcheck] lock-order inversion: "
            + " -> ".join(names)
            + f" (thread {violation['thread']})",
            file=sys.stderr,
        )
        if self.raise_on_cycle:
            # undo the held-stack push: the proxy releases the real lock
            # before propagating, so the bookkeeping must match
            self.note_released(token)
            raise LockOrderError(
                "lock-order inversion: " + " -> ".join(names))

    def note_released(self, token: int) -> None:
        held = self._held()
        # release order need not mirror acquire order; drop the LAST
        # occurrence (matches RLock reentry bookkeeping)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == token:
                del held[i]
                return

    def _cycle_through(self, src: int, dst: int) -> list[int] | None:
        """A path src ~> dst in the edge graph (call under self._mu).
        Adding dst->src then closes the cycle the caller reports."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._adj.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


class _CheckedLock:
    """Delegating lock proxy. Everything not overridden falls through to
    the real lock — including the Condition protocol methods, which MUST
    bypass tracking (see module docstring)."""

    __slots__ = ("_ccfd_inner", "_ccfd_graph", "_ccfd_token")

    def __init__(self, inner: Any, graph: LockGraph, token: int):
        object.__setattr__(self, "_ccfd_inner", inner)
        object.__setattr__(self, "_ccfd_graph", graph)
        object.__setattr__(self, "_ccfd_token", token)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._ccfd_inner.acquire(blocking, timeout)
        if got:
            try:
                self._ccfd_graph.note_acquired(self._ccfd_token)
            except LockOrderError:
                # never leave the real lock held behind a raising acquire:
                # the caller's `with` will not run __exit__
                self._ccfd_inner.release()
                raise
        return got

    def release(self) -> None:
        self._ccfd_inner.release()
        self._ccfd_graph.note_released(self._ccfd_token)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._ccfd_inner.locked()

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_ccfd_inner"), name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"<CheckedLock #{self._ccfd_token} "
                f"wrapping {self._ccfd_inner!r}>")


# -- global install surface --------------------------------------------------

_global_graph: LockGraph | None = None


def _caller_label() -> str:
    """Construction site of the lock being created: the first frame
    outside this module and threading.py. Diagnostic only — identity is
    the per-instance token."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(("lockcheck.py", "threading.py")):
            rel = fn
            for marker in ("ccfd_tpu/", "tests/", "tools/"):
                i = fn.rfind(marker)
                if i >= 0:
                    rel = fn[i:]
                    break
            return f"{rel}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"  # pragma: no cover


def install(raise_on_cycle: bool = True,
            scope: tuple[str, ...] = ("ccfd_tpu/",)) -> LockGraph:
    """Arm the sanitizer process-wide: locks constructed after this call
    FROM CODE MATCHING ``scope`` (substring of the constructing frame's
    filename) are checked; everything else — jax internals, stdlib
    machinery like queue.Queue — gets a real lock, keeping foreign lock
    graphs out of ours and the overhead on our own code only. Idempotent;
    returns the global graph."""
    global _global_graph
    if _global_graph is not None:
        return _global_graph
    graph = LockGraph(raise_on_cycle=raise_on_cycle)
    _global_graph = graph

    def _in_scope() -> str | None:
        """Constructing site when it falls under ``scope``, else None."""
        label = _caller_label()
        return label if any(m in label for m in scope) else None

    def make_lock() -> Any:
        site = _in_scope()
        if site is None:
            return _REAL_LOCK()
        return _CheckedLock(_REAL_LOCK(), graph, graph.new_token(site))

    def make_rlock() -> Any:
        site = _in_scope()
        if site is None:
            return _REAL_RLOCK()
        return _CheckedLock(_REAL_RLOCK(), graph, graph.new_token(site))

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.RLock = make_rlock  # type: ignore[assignment]
    return graph


def uninstall() -> None:
    """Restore the real factories. Already-wrapped locks keep working
    (their graph just stops gaining edges that matter)."""
    global _global_graph
    threading.Lock = _REAL_LOCK  # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
    _global_graph = None


def installed() -> bool:
    return _global_graph is not None


def violations() -> list[dict[str, Any]]:
    """Inversions the global sanitizer has recorded (empty when healthy
    or not armed)."""
    return list(_global_graph.violations) if _global_graph else []


def armed_from_env() -> bool:
    return bool(os.environ.get("CCFD_LOCKCHECK"))
