"""The seven ccfd-lint rules: review findings as named invariants.

Each rule's ``invariant`` is the one-line law; ``motivated_by`` names the
PR / review finding that kept re-finding the defect class by hand (the
table in ARCHITECTURE.md "Static analysis & invariants" is generated
from these strings conceptually — keep them in sync).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from ccfd_tpu.analysis.core import FileContext, Finding, Rule, register

# -- shared AST helpers ------------------------------------------------------


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``self._breaker.allow``,
    ``np.savez``, ``time.time``. Unresolvable parts render as ``?``."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append("()")
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def _is_time_time(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    # "_time.time" (the local-alias idiom) ends with "time.time" too;
    # "datetime.time" is a constructor, not a clock read
    return d.endswith("time.time") and not d.endswith("datetime.time")


def _functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# -- rule 1: durability-seam -------------------------------------------------

# modules that ARE the durability layer (or predate it with their own
# integrity discipline) — writes inside them are the seam, not a bypass
_SEAM_FILES = (
    "runtime/durability.py",
    # the CRC-framed segment log is the durability seam's own ancestor
    # ("the bus log already shows the house style" — durability.py
    # docstring); its tmp+fsync+rename compaction is the idiom itself
    "bus/log.py",
)
# interchange documents read by humans/Grafana/kubectl keep plain bodies
# by design (durability.write_json_interchange exists for the checksummed
# variant; generated dashboards and k8s manifests are build artifacts
# reviewed in git, not runtime state)
_INTERCHANGE_FILES = ("observability/dashboards.py", "platform/k8s.py")


@register
class DurabilitySeamRule(Rule):
    name = "durability-seam"
    invariant = ("every persistent artifact is written/renamed through "
                 "runtime/durability.py (atomic tmp+fsync+rename, "
                 "checksummed frame, last-good generations)")
    motivated_by = ("PR 13: eight hand-rolled tmp+rename copies all "
                    "skipped the fsync, so a power loss could lose both "
                    "the old and the new artifact")

    _WRITE_MODES = {"w", "wb", "w+", "wb+", "w+b"}

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path.endswith(_SEAM_FILES) or ctx.path.endswith(
                _INTERCHANGE_FILES):
            return []
        out: list[Finding] = []
        # names bound to io.BytesIO(): np.savez into a memory buffer is
        # the SANCTIONED pattern (buffer bytes then durability.write_artifact)
        membuf_names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _dotted(node.value.func) in ("io.BytesIO", "BytesIO")):
                membuf_names.add(node.targets[0].id)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _dotted(node.func)
            if fn == "open":
                mode = None
                if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                        mode = kw.value.value
                if isinstance(mode, str) and mode in self._WRITE_MODES:
                    out.append(ctx.finding(
                        self.name, node,
                        f"open(mode={mode!r}) writes a persistent file "
                        "outside the durability seam; use "
                        "durability.atomic_write_bytes/write_artifact (or "
                        "write_json_interchange for human/Grafana docs)"))
            elif fn in ("os.rename", "os.replace"):
                out.append(ctx.finding(
                    self.name, node,
                    f"{fn}() outside the durability seam: atomic swaps "
                    "belong to durability.write_artifact (quarantine "
                    "renames are the sanctioned exception — say so in a "
                    "pragma)"))
            elif fn == "json.dump":
                out.append(ctx.finding(
                    self.name, node,
                    "json.dump() to a file handle bypasses the durability "
                    "seam; use durability.write_json_artifact or "
                    "write_json_interchange"))
            elif fn.split(".")[-1] in ("savez", "savez_compressed") and (
                    fn.split(".")[0] in ("np", "numpy", "onp")):
                first = node.args[0] if node.args else None
                if isinstance(first, ast.Name) and first.id in membuf_names:
                    continue  # buffered-then-write_artifact pattern
                out.append(ctx.finding(
                    self.name, node,
                    f"{fn}() straight to a path skips the checksummed "
                    "frame; savez into io.BytesIO() and hand the bytes to "
                    "durability.write_artifact"))
        return out


# -- rule 2: monotonic-durations ---------------------------------------------


@register
class MonotonicDurationsRule(Rule):
    name = "monotonic-durations"
    invariant = ("durations are measured with perf_counter/monotonic "
                 "pairs; time.time() is for wall-clock timestamps that "
                 "ride records and artifacts, never for interval math")
    motivated_by = ("PR 2: an NTP step mid-benchmark produced a negative "
                    "router batch latency and a corrupted histogram")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: list[Finding] = []
        wall_names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _is_time_time(node.value)):
                wall_names.add(node.targets[0].id)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            sides = (node.left, node.right)
            direct = any(
                isinstance(s, ast.Call) and _is_time_time(s) for s in sides)
            both_wall = all(
                isinstance(s, ast.Name) and s.id in wall_names for s in sides)
            if direct or both_wall:
                out.append(ctx.finding(
                    self.name, node,
                    "time.time() pair used as a duration (NTP step = "
                    "negative latency); use time.perf_counter() — if this "
                    "is wall-clock math against a record/artifact "
                    "timestamp, say so in a pragma"))
        return out


# -- rule 3: counted-drops ---------------------------------------------------

_DROP_SCOPES = ("/router/", "/bus/", "/serving/", "/observability/",
                "/fleet/")
_LOG_METHODS = frozenset(
    ("debug", "info", "warning", "error", "exception", "critical", "log"))


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [_dotted(e) for e in t.elts]
    else:
        names = [_dotted(t)]
    return any(n.split(".")[-1] in ("Exception", "BaseException")
               for n in names)


def _body_accounts(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            # set_exception delivers the error to a waiter's future — it
            # re-raises at the await point, the opposite of a swallow
            if (attr in ("inc", "observe", "set_exception")
                    or attr in _LOG_METHODS):
                return True
    return False


@register
class CountedDropsRule(Rule):
    name = "counted-drops"
    invariant = ("no silent caps: a broad except that drops work in "
                 "router/bus/serving/observability/fleet must re-raise, "
                 "log via slog, or increment a *_total counter")
    motivated_by = ("recurring since PR 1; PR 6 made it the overload "
                    "plane's core guarantee (every shed is counted by "
                    "priority) and reviews still kept finding bare "
                    "swallows")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not any(s in f"/{ctx.path}" for s in _DROP_SCOPES):
            return []
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _handler_is_broad(node):
                continue
            if _body_accounts(node):
                continue
            out.append(ctx.finding(
                self.name, node,
                "broad except swallows without accounting: re-raise, log "
                "via slog (trace-correlated), or increment a named "
                "*_dropped_total/*_errors_total counter"))
        return out


# -- rule 4: metric-naming ---------------------------------------------------

# Names mirrored verbatim from the reference's Grafana boards (SURVEY.md
# §5): the dashboards are the contract, so these keep their reference
# spelling. They are gauges, which the convention already admits — the
# set is exported for the contract test's registered-name cross-check
# and as documentation of WHY these names look nothing like the rest.
REFERENCE_BOARD_NAMES = frozenset((
    "proba_1", "Amount", "V17", "V10",  # ModelPrediction.json:96-322
))
# Kind-keyed exemptions: a (kind, name) pair predating the rule whose
# rename would break checked-in dashboards and recorded bench history.
# Keyed by kind so the exemption cannot silently re-admit a FUTURE
# metric registered under the same name as a different kind.
GRANDFATHERED_NAMES = frozenset((
    # Router board; a rows-count histogram predating the suffix rule
    ("histogram", "router_batch_size"),
))

_HISTOGRAM_SUFFIXES = ("_seconds", "_bytes", "_amount", "_rows", "_ms")


def metric_name_ok(kind: str, name: str) -> str | None:
    """None when ``name`` satisfies the convention for ``kind``, else the
    violation message. Shared with the dashboard-contract test
    (tests/test_observability.py) so the conventions can't drift between
    the linter and the test suite."""
    if (kind, name) in GRANDFATHERED_NAMES:
        return None
    if kind == "counter" and not name.endswith("_total"):
        return f"counter {name!r} must end _total"
    if kind == "gauge" and name.endswith("_total"):
        return f"gauge {name!r} must not end _total (that suffix promises monotonicity)"
    if kind == "histogram" and not name.endswith(_HISTOGRAM_SUFFIXES):
        return (f"histogram {name!r} needs a unit suffix "
                f"({'/'.join(_HISTOGRAM_SUFFIXES)})")
    return None


@register
class MetricNamingRule(Rule):
    name = "metric-naming"
    invariant = ("counters end _total, histograms carry a unit suffix, "
                 "gauges never claim _total; reference-board names are "
                 "the only exemption")
    motivated_by = ("the round-7 dashboard↔metric contract test kept "
                    "catching misnamed series only AFTER a board "
                    "referenced them; this moves the check to the "
                    "registration site")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge", "histogram")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            err = metric_name_ok(node.func.attr, node.args[0].value)
            if err:
                out.append(ctx.finding(self.name, node, err))
        return out


# -- rule 5: breaker-outcome -------------------------------------------------


def _is_breaker_receiver(dotted: str) -> bool:
    last = dotted.split(".")[-1]
    return "breaker" in last or last == "br"


def _stmt_records_unconditionally(stmt: ast.stmt) -> bool:
    """Does this statement contain a record_success/record_failure call
    NOT nested under further branching? (A record inside an If/Try within
    the statement is conditional — a different path.)"""
    def scan(node: ast.AST) -> bool:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.If, ast.Try, ast.While, ast.For,
                                  ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ExceptHandler)):
                continue
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in ("record_success",
                                            "record_failure")):
                return True
            if scan(child):
                return True
        return False

    return scan(stmt)


@register
class BreakerOutcomeRule(Rule):
    name = "breaker-outcome"
    invariant = ("a breaker-gated call path records exactly one outcome: "
                 "an admitted HALF_OPEN probe that records zero outcomes "
                 "wedges the circuit open; two outcomes double-count the "
                 "window")
    motivated_by = ("PR 6 review: a non-200 response path recorded no "
                    "outcome, leaking the probe slot and wedging the "
                    "scorer edge open until restart")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: list[Finding] = []
        for fn in _functions(ctx.tree):
            gates = [
                n for n in ast.walk(fn)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "allow"
                and _is_breaker_receiver(_dotted(n.func.value))
            ]
            if not gates:
                continue
            successes = failures = 0
            for n in ast.walk(fn):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)):
                    if n.func.attr == "record_success":
                        successes += 1
                    elif n.func.attr == "record_failure":
                        failures += 1
            if successes == 0 and failures == 0:
                out.append(ctx.finding(
                    self.name, gates[0],
                    f"{fn.name}() gates on breaker.allow() but never "
                    "records an outcome: an admitted HALF_OPEN probe "
                    "leaks and the circuit wedges open"))
                continue
            if successes == 0 or failures == 0:
                missing = "record_success" if successes == 0 else "record_failure"
                out.append(ctx.finding(
                    self.name, gates[0],
                    f"{fn.name}() gates on breaker.allow() but has no "
                    f"{missing} path: one outcome class is never fed back "
                    "into the window"))
            # double-record: two unconditional records in one straight-line
            # body means some path records twice
            for node in ast.walk(fn):
                body = getattr(node, "body", None)
                if not isinstance(body, list):
                    continue
                hits = [s for s in body
                        if isinstance(s, ast.stmt)
                        and _stmt_records_unconditionally(s)]
                if len(hits) >= 2:
                    out.append(ctx.finding(
                        self.name, hits[1],
                        f"{fn.name}() records two breaker outcomes on one "
                        "straight-line path: the window double-counts "
                        "this call"))
        return out


# -- rule 6: hot-path-sync ---------------------------------------------------

_SYNC_CALLS = frozenset((
    "np.asarray", "numpy.asarray", "onp.asarray", "jax.device_get",
))
_SYNC_ATTRS = frozenset(("item", "block_until_ready", "tolist"))
# the router's score->route seam (the functions between the device
# dispatch and _route): with the fused decision kernel the verdict comes
# back in ONE packed transfer, so the only sync these functions may
# contain is materializing a dispatch result — np.asarray(<call>().
# Any sync on an already-bound name (np.asarray(proba), proba.tolist())
# is a NEW host round trip sneaking in between score and route.
_SEAM_FUNCS = frozenset(("_score_tiered", "_score_direct", "_score_batch"))


@register
class HotPathSyncRule(Rule):
    name = "hot-path-sync"
    invariant = ("functions marked `# ccfd-lint: hot-path` must not "
                 "force a device->host sync (np.asarray/.item()/float()/"
                 "block_until_ready): the overlap IS the throughput. "
                 "The router's score->route seam (_score_tiered/"
                 "_score_direct/_score_batch in router/router.py) is "
                 "implicitly hot, with ONE allowed sync shape: "
                 "np.asarray(<dispatch call>) — the transfer itself")
    motivated_by = ("PR 8: one stray float(proba) in the seq dispatch "
                    "loop serialized the whole overlapped dataflow back "
                    "to 2k tx/s; PR 19: the fused decision kernel deletes "
                    "the host rules pass, and the seam check keeps a "
                    "second sync from growing back between score and route")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        out: list[Finding] = []
        seam_file = ctx.path.endswith("router/router.py")
        for fn in _functions(ctx.tree):
            marked = (fn.lineno in ctx.hot_path_lines
                      or (fn.lineno - 1) in ctx.hot_path_lines
                      or any(d.lineno - 1 in ctx.hot_path_lines
                             or d.lineno in ctx.hot_path_lines
                             for d in fn.decorator_list))
            seam = seam_file and fn.name in _SEAM_FUNCS
            if not marked and not seam:
                continue
            where = ("score->route seam" if seam and not marked
                     else "hot-path")
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                fname = _dotted(node.func)
                if fname in _SYNC_CALLS:
                    if (seam and not marked and node.args
                            and isinstance(node.args[0], ast.Call)):
                        # the single allowed seam sync: materializing a
                        # dispatch result as it crosses to the host
                        continue
                    out.append(ctx.finding(
                        self.name, node,
                        f"{fname}() inside {where} {fn.name}(): forces a "
                        "device->host sync"))
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_ATTRS
                        and not node.args):
                    out.append(ctx.finding(
                        self.name, node,
                        f".{node.func.attr}() inside {where} {fn.name}():"
                        " forces a device->host sync"))
                elif (fname == "float" and node.args
                        and not isinstance(node.args[0], ast.Constant)):
                    out.append(ctx.finding(
                        self.name, node,
                        f"float(...) inside {where} {fn.name}(): on a "
                        "device array this blocks on the transfer"))
        return out


# -- rule 7: lock-order (static half) ----------------------------------------

_LOCK_ATTRS = ("lock", "locks", "mu", "mutex")


def _lock_label(ctx: FileContext, classname: str, expr: ast.expr) -> str | None:
    """A stable node label for a lock-acquiring ``with`` item, or None
    when the expression isn't lock-shaped. ``self._lock`` ->
    ``path::Class._lock``; ``self._locks[i]`` -> ``path::Class._locks[]``."""
    suffix = ""
    if isinstance(expr, ast.Subscript):
        expr = expr.value
        suffix = "[]"
    if not isinstance(expr, ast.Attribute):
        return None
    attr = expr.attr
    base = attr.lstrip("_").lower()
    if not any(base == a or base.endswith("_" + a) for a in _LOCK_ATTRS):
        return None
    return f"{ctx.path}::{classname}.{attr}{suffix}"


class _LockNestingVisitor(ast.NodeVisitor):
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.class_stack: list[str] = []
        self.held: list[str] = []
        # (src, dst) -> (path, line) of an example acquisition site
        self.edges: dict[tuple[str, str], tuple[str, int]] = {}

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_with(self, node) -> None:
        n_added = 0
        for item in node.items:
            label = _lock_label(
                self.ctx, self.class_stack[-1] if self.class_stack else "<module>",
                item.context_expr)
            if label is None:
                continue
            for h in self.held:
                if h != label:
                    self.edges.setdefault(
                        (h, label), (self.ctx.path, node.lineno))
            # push IMMEDIATELY: `with a, b:` acquires a then b at runtime,
            # so item i must see items < i as held — appending after the
            # loop would miss every edge inside one multi-item with
            self.held.append(label)
            n_added += 1
        self.generic_visit(node)
        if n_added:
            del self.held[-n_added:]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with


@register
class LockOrderRule(Rule):
    name = "lock-order"
    invariant = ("the lexical lock-acquisition graph over `with <lock>` "
                 "sites is acyclic: a cycle is a deadlock waiting for the "
                 "right interleaving (the runtime sanitizer extends this "
                 "through method calls and across modules)")
    motivated_by = ("PR 8's eviction-stamp race and PR 12's publish-gate "
                    "leak were both lock-order bugs that only live kill-"
                    "storm drills caught; the runtime sanitizer "
                    "(analysis/lockcheck.py) is this rule's dynamic half")
    scope = "project"

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        edges: dict[tuple[str, str], tuple[str, int]] = {}
        for ctx in ctxs:
            v = _LockNestingVisitor(ctx)
            v.visit(ctx.tree)
            for k, site in v.edges.items():
                edges.setdefault(k, site)
        adj: dict[str, set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        cycles = self._find_cycles(adj)
        by_path = {c.path: c for c in ctxs}
        out: list[Finding] = []
        for cycle in cycles:
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            site_path, site_line = edges[pairs[-1]]
            ctx = by_path.get(site_path)
            where = " -> ".join(cycle + [cycle[0]])
            f = Finding(
                rule=self.name, path=site_path, line=site_line, col=0,
                message=(f"lock-order cycle: {where} — some path acquires "
                         "these in the opposite order; pick one global "
                         "order or drop to a lock-free handoff (cross-"
                         "module inversions through method calls are the "
                         "runtime sanitizer's job: CCFD_LOCKCHECK=1)"),
                snippet=(ctx.snippet_at(site_line) if ctx else ""))
            out.append(f)
        return out

    @staticmethod
    def _find_cycles(adj: dict[str, set[str]]) -> list[list[str]]:
        """Elementary cycles via DFS, deduplicated by node set. The graph
        is tiny (dozens of nodes), so simple beats clever."""
        cycles: list[list[str]] = []
        seen_sets: set[frozenset[str]] = set()

        def dfs(start: str, node: str, path: list[str],
                visiting: set[str]) -> None:
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) >= 2:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        # canonical rotation: start at the smallest node
                        i = path.index(min(path))
                        cycles.append(path[i:] + path[:i])
                elif nxt not in visiting and nxt > start:
                    # only explore nodes > start: each cycle found once,
                    # from its smallest member
                    visiting.add(nxt)
                    dfs(start, nxt, path + [nxt], visiting)
                    visiting.discard(nxt)

        for start in sorted(adj):
            dfs(start, start, [start], {start})
        return cycles
